"""Asynchronous exchange: dispatch collectives, verify at stage
boundaries, stage oversized payloads through host RAM.

Three coordinated pieces (ROADMAP open item 4, "Theseus: ... Optimized
for Efficient Data Movement", PAPERS.md):

* :class:`AsyncExchangeHandle` — the deferred tail of one
  exchange-bearing launch.  XLA dispatch is asynchronous by
  construction; what serializes the host today is the POST-launch
  verification (the speculative slot-overflow flag fetch).  A handle
  captures that verification as a callback and the planner resolves it
  at the next stage boundary instead of inline, so the fused compute of
  the next stage dispatches while the collective for this one is still
  in flight.  ``exchangeOverlapMs`` (dispatch -> resolve start) over
  ``exchangeWallMs`` (dispatch -> resolve end) is the overlap fraction
  the MULTICHIP dryrun reports.

* :class:`ExchangeWindow` — the budgeted in-flight window.  Admitting a
  handle past ``inflightWindowBytes`` resolves the oldest pending
  handles first (FIFO), so a deep plan cannot pin unbounded HBM in
  unverified exchange buffers.  In-flight bytes are charged to the
  query's serving context (serving/context.py) while pending.

* :func:`host_staged_partition` — the host-RAM staging tier.  When a
  payload exceeds the staging threshold the exchange never rides the
  device collective: rows are pulled to host, repartitioned with the
  same murmur mix the device kernels use, round-tripped through the
  spill tier's frame codec (compressed — the pinned-bounce-buffer
  analog), and pushed back already co-located.  An oversized shuffle
  lands in host RAM instead of failing over to the split rung.

Cooperative cancellation: ``resolve`` runs a watchdog checkpoint and
fires the ``exchange.async.resolve`` injection point under a watchdog
section, so the recovery ladder and deadline monitor keep firing on the
async path exactly as they do on the synchronous one.  A deferred
overflow (the EMA slot was too small and downstream compute already
consumed the truncated frame) raises
:class:`~spark_rapids_tpu.robustness.faults.AsyncExchangeOverflow` —
RETRYABLE: the ladder re-drives the whole attempt (synchronously — the
window is never armed on recovery re-attempts) and the slot planner has
already latched the site back onto the stats-sized path: results are
never wrong, only re-driven.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


# ------------------------------------------------------ overlap metrics --

class ExchangeOverlapMetrics:
    """Cumulative async-exchange counters (one per session, process
    fallback for bare kernel use — the ShuffleWireMetrics discipline).
    Per-query deltas ride the QueryEnd ``shuffle`` dict."""

    FIELDS = ("asyncExchanges", "syncExchanges", "exchangeOverlapMs",
              "exchangeWallMs", "deferredOverflows", "windowEvictions",
              "hostStagedExchanges", "hostStagedBytes",
              "hostStagedRawBytes", "inflightPeakBytes")

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, float] = {k: 0 for k in self.FIELDS}

    def record_resolve(self, overlap_ns: int, wall_ns: int) -> None:
        with self._lock:
            c = self.counters
            c["asyncExchanges"] += 1
            c["exchangeOverlapMs"] += overlap_ns / 1e6
            c["exchangeWallMs"] += wall_ns / 1e6

    def record_sync(self) -> None:
        with self._lock:
            self.counters["syncExchanges"] += 1

    def record_deferred_overflow(self) -> None:
        with self._lock:
            self.counters["deferredOverflows"] += 1

    def record_eviction(self) -> None:
        with self._lock:
            self.counters["windowEvictions"] += 1

    def record_staging(self, staged_bytes: int, raw_bytes: int) -> None:
        with self._lock:
            c = self.counters
            c["hostStagedExchanges"] += 1
            c["hostStagedBytes"] += int(staged_bytes)
            c["hostStagedRawBytes"] += int(raw_bytes)

    def note_inflight(self, inflight_bytes: int) -> None:
        with self._lock:
            c = self.counters
            c["inflightPeakBytes"] = max(c["inflightPeakBytes"],
                                         int(inflight_bytes))

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {k: (round(v, 3) if isinstance(v, float) else v)
                    for k, v in self.counters.items()}

    @staticmethod
    def delta(after: Dict[str, float], before: Dict[str, float]
              ) -> Dict[str, float]:
        out = {}
        for k in after:
            d = after.get(k, 0) - before.get(k, 0)
            out[k] = round(d, 3) if isinstance(d, float) else d
        # peak is a high-water mark, not a counter: report the absolute
        out["inflightPeakBytes"] = after.get("inflightPeakBytes", 0)
        return out


_default_overlap = None


def overlap_metrics_for_session(session=None) -> ExchangeOverlapMetrics:
    global _default_overlap
    if session is None:
        from spark_rapids_tpu.api.session import TpuSession
        session = TpuSession._active
    if session is None:
        if _default_overlap is None:
            _default_overlap = ExchangeOverlapMetrics()
        return _default_overlap
    m = getattr(session, "exchange_overlap_metrics", None)
    if m is None:
        m = ExchangeOverlapMetrics()
        session.exchange_overlap_metrics = m
    return m


# -------------------------------------------------------------- handles --

class AsyncExchangeHandle:
    """The deferred tail of one exchange-bearing launch.

    ``verify`` is the host-side verification the synchronous path would
    have run inline (overflow-flag fetch + rerun decision); None for
    stats-sized launches whose slot is already proven.  ``resolve`` is
    idempotent and is the ONLY place the verification runs — it fires
    the ``exchange.async.resolve`` injection point under a watchdog
    section and runs a cancellation checkpoint first, so chaos rules
    and query deadlines land here exactly as on a synchronous sync."""

    def __init__(self, site, payload_bytes: int = 0,
                 verify: Optional[Callable[[], None]] = None,
                 metrics: Optional[ExchangeOverlapMetrics] = None,
                 on_done: Optional[Callable[["AsyncExchangeHandle"],
                                            None]] = None):
        self.site = site
        self.payload_bytes = int(payload_bytes)
        self._verify = verify
        self._metrics = metrics or overlap_metrics_for_session()
        self._on_done = on_done
        self.dispatch_ns = time.perf_counter_ns()
        self.resolved = False
        self.overflowed = False

    def resolve(self) -> None:
        if self.resolved:
            return
        self.resolved = True
        t_start = time.perf_counter_ns()
        try:
            from spark_rapids_tpu.robustness import watchdog
            from spark_rapids_tpu.robustness.inject import fire
            watchdog.checkpoint()
            with watchdog.section("exchange.async.resolve"):
                fire("exchange.async.resolve")
                if self._verify is not None:
                    self._verify()
        finally:
            t_end = time.perf_counter_ns()
            overlap_ns = t_start - self.dispatch_ns
            self._metrics.record_resolve(
                overlap_ns=overlap_ns,
                wall_ns=t_end - self.dispatch_ns)
            # the in-flight window (dispatch -> resolve start) is the
            # span-level form of exchangeOverlapMs: exported on the
            # async track and recorded as the site's overlap_ms
            # observation, so the PR9 overlap number is reproducible
            # from spans alone
            from spark_rapids_tpu.utils import tracing
            if tracing._armed:
                tracing.emit_span("exchange.async.inflight",
                                  self.dispatch_ns, overlap_ns,
                                  site=self.site)
                tracing.observe_site(self.site,
                                     overlap_ms=overlap_ns / 1e6)
            if self._on_done is not None:
                self._on_done(self)

    def discard(self) -> None:
        """Drop without verifying — only for an attempt that is already
        failing (the ladder re-runs everything; unverified buffers just
        release).  Counted as resolved so the window's byte budget
        frees."""
        if self.resolved:
            return
        self.resolved = True
        if self._on_done is not None:
            self._on_done(self)


class ExchangeWindow:
    """Budgeted FIFO window of unresolved exchange handles.

    One per planner run.  ``admit`` resolves the oldest pending handles
    until the new payload fits the byte budget (backpressure by
    verification, not by blocking — everything runs on the driving
    thread, so resolving IS yielding the window).  Pending bytes are
    charged to the query's serving context while in flight."""

    def __init__(self, max_bytes: int,
                 metrics: Optional[ExchangeOverlapMetrics] = None):
        self.max_bytes = max(int(max_bytes), 1)
        self.metrics = metrics or overlap_metrics_for_session()
        self.pending: "deque[AsyncExchangeHandle]" = deque()
        self.inflight_bytes = 0

    def _charge(self, delta: int) -> None:
        self.inflight_bytes += delta
        if delta > 0:
            self.metrics.note_inflight(self.inflight_bytes)
        from spark_rapids_tpu.serving import context as qc
        ctx = qc.current()
        if ctx is not None:
            ctx.charge_exchange_inflight(delta)

    def _done(self, handle: AsyncExchangeHandle) -> None:
        try:
            self.pending.remove(handle)
        except ValueError:
            pass
        self._charge(-handle.payload_bytes)

    def admit(self, site, payload_bytes: int = 0,
              verify: Optional[Callable[[], None]] = None
              ) -> AsyncExchangeHandle:
        """Create, budget, and enqueue a handle for a just-dispatched
        exchange.  Over-budget admission resolves oldest-first (the
        bounded in-flight window)."""
        if self.pending and \
                self.inflight_bytes + payload_bytes > self.max_bytes:
            # the in-window wait: verification of older handles is the
            # backpressure this admit pays before dispatching onward
            from spark_rapids_tpu.utils import tracing
            with tracing.span("exchange.window.wait"):
                while self.pending and \
                        self.inflight_bytes + payload_bytes > \
                        self.max_bytes:
                    self.metrics.record_eviction()
                    self.pending[0].resolve()
        h = AsyncExchangeHandle(site, payload_bytes, verify,
                                metrics=self.metrics, on_done=self._done)
        self.pending.append(h)
        self._charge(h.payload_bytes)
        return h

    def resolve_all(self) -> None:
        """The stage-boundary barrier: verify every pending exchange
        (FIFO).  Raises the first verification fault — the recovery
        ladder re-drives the query; remaining handles are discarded by
        the caller's ``discard_all``."""
        while self.pending:
            self.pending[0].resolve()

    def discard_all(self) -> None:
        while self.pending:
            self.pending[0].discard()


# The driving thread's active window (one per distributed attempt,
# parallel/dist_planner.py).  Thread-local on purpose: a window's
# handles verify on the thread that dispatched them — concurrent
# queries (serving/) each carry their own — and stage-boundary hooks on
# OTHER threads (a pipeline worker) see None and no-op.
_tls = threading.local()


def current_window() -> Optional[ExchangeWindow]:
    return getattr(_tls, "window", None)


def set_current_window(window: Optional[ExchangeWindow]) -> None:
    _tls.window = window


def resolve_pending() -> None:
    """Stage-boundary hook: verify every pending async exchange of the
    calling thread's active window.  No-op without one — safe to call
    from any engine stage boundary (exec/pipeline.py batch gets,
    exec/fusion.py fused-stage batch loops, DistPlanner checkpoint
    saves and collect)."""
    w = current_window()
    if w is not None and w.pending:
        w.resolve_all()


# -------------------------------------------------- host-RAM staging --

def staging_threshold(session=None) -> int:
    """Effective host-staging threshold in bytes (0 = staging off —
    the conf knob is the ONLY opt-in; defaults must bit-reproduce the
    pre-staging engine).  When staging IS enabled, the query's serving
    memory budget tightens it (an exchange the budget could never hold
    should stage, not march into the spill/reject ladder)."""
    from spark_rapids_tpu.config import rapids_conf as rc
    if session is None:
        from spark_rapids_tpu.api.session import TpuSession
        session = TpuSession._active
    if session is None:
        return 0
    thr = int(session.conf.get(rc.EXCHANGE_HOST_STAGING_THRESHOLD))
    if not thr:
        return 0
    from spark_rapids_tpu.serving import context as qc
    ctx = qc.current()
    if ctx is not None and ctx.memory_budget:
        thr = min(thr, int(ctx.memory_budget))
    return thr


# the host-side murmur port lives NEXT TO the device kernels it must
# stay bit-parity with (parallel/partitioning.py); staging callers
# import it from here
from spark_rapids_tpu.parallel.partitioning import (  # noqa: E402,F401
    host_hash_partition_ids)


def frame_roundtrip(cols: Sequence[Tuple[np.ndarray, np.ndarray]]
                    ) -> Tuple[List[Tuple[np.ndarray, np.ndarray]],
                               int, int]:
    """Round-trip column buffers through the spill tier's frame codec
    (native zero-RLE/LZB when built, pure-python fallback otherwise) —
    the pinned-host transit.  Returns (cols back, compressed bytes,
    raw bytes); CRC/structure verification is the codec's own."""
    from spark_rapids_tpu.native import deserialize_batch, serialize_batch
    nrows = int(cols[0][0].shape[0]) if cols else 0
    payload = []
    raw = 0
    for values, validity in cols:
        payload.append((0, values, validity, None))
        raw += values.nbytes + (validity.nbytes
                               if validity is not None else 0)
    blob = serialize_batch(nrows, payload, compress=True)
    _, back = deserialize_batch(blob)
    out = []
    for (values, validity), (_, data_u8, valid_u8, _) in zip(cols, back):
        v = np.frombuffer(bytes(data_u8), dtype=values.dtype) \
            if data_u8 is not None and len(data_u8) \
            else np.zeros(0, dtype=values.dtype)
        m = np.frombuffer(bytes(valid_u8), dtype=np.bool_) \
            if valid_u8 is not None and len(valid_u8) \
            else np.ones(v.shape[0], dtype=np.bool_)
        out.append((v.copy(), m.copy()))
    return out, len(blob), raw


def host_staged_partition(cols_host: Sequence[Tuple[np.ndarray,
                                                    np.ndarray]],
                          counts: np.ndarray,
                          pids_host: np.ndarray,
                          nshards: int,
                          out_capacity: Optional[int] = None,
                          session=None):
    """Repartition leading-axis-sharded host columns by destination —
    the host-RAM staging path for an oversized exchange.

    ``cols_host``: [(values [nshards*cap], validity [nshards*cap])];
    ``counts``: live rows per shard; ``pids_host``: destination per row
    (same layout).  Rows round-trip through the frame codec; the result
    is the post-exchange layout ([nshards*out_cap] buffers + per-shard
    counts) ready for jnp.asarray placement.  Fires the
    ``exchange.host_staging`` injection point under a watchdog section
    (retryable through the ladder like any exchange fault)."""
    import time as _time

    from spark_rapids_tpu.columnar.column import bucket_capacity
    from spark_rapids_tpu.robustness import grayfailure, watchdog
    from spark_rapids_tpu.robustness.inject import fire
    # the hedge leg of a hedged_call routes through
    # exchange.host_staging.hedge — the healthy-survivor path a sick
    # host's armed delay rules do not target
    point = grayfailure.hedge_point("exchange.host_staging")
    t0 = _time.monotonic()
    with watchdog.section(point):
        fire(point)
        cap = pids_host.shape[0] // nshards
        live = np.zeros(nshards * cap, dtype=bool)
        for s in range(nshards):
            live[s * cap: s * cap + int(counts[s])] = True
        pids = np.where(live, pids_host, nshards)  # dead rows sort last
        # stable destination sort keeps source-shard row order within a
        # destination (same order the collective's compaction produces)
        order = np.argsort(pids, kind="stable")
        order = order[: int(live.sum())]
        dest = pids[order]
        dest_counts = np.bincount(dest, minlength=nshards)[:nshards]
        staged = [(np.ascontiguousarray(v[order]),
                   np.ascontiguousarray(
                       m[order] if m is not None
                       else np.ones(order.shape[0], dtype=bool)))
                  for v, m in cols_host]
        staged, staged_bytes, raw_bytes = frame_roundtrip(staged)
        overlap_metrics_for_session(session).record_staging(
            staged_bytes, raw_bytes)
        out_cap = out_capacity or bucket_capacity(
            max(int(dest_counts.max()) if dest_counts.size else 1, 1),
            minimum=8)
        starts = np.concatenate([[0], np.cumsum(dest_counts)[:-1]])
        out_cols = []
        for v, m in staged:
            vbuf = np.zeros(nshards * out_cap, dtype=v.dtype)
            mbuf = np.zeros(nshards * out_cap, dtype=bool)
            for d in range(nshards):
                n = int(dest_counts[d])
                sl = slice(int(starts[d]), int(starts[d]) + n)
                vbuf[d * out_cap: d * out_cap + n] = v[sl]
                mbuf[d * out_cap: d * out_cap + n] = m[sl]
            out_cols.append((vbuf, mbuf))
        if session is None:
            from spark_rapids_tpu.api.session import TpuSession
            session = TpuSession._active
        grayfailure.note_wall(
            session, "exchange.host_staging",
            (_time.monotonic() - t0) * 1e3)
        return out_cols, dest_counts.astype(np.int32), staged_bytes


def stage_host_side(flat, hist, key_idx, num_buckets: int, nshards: int,
                    lut=None):
    """Materialize one exchange side's device buffers on host, recompute
    its partition ids with the bit-parity murmur mix, and repartition
    through the frame codec — shared by the aggregate and join staging
    paths so the host-side hashing/validity discipline cannot diverge.

    ``flat``: [(values, validity-or-None)] device buffers; ``hist``:
    the side's [src, dst] histogram (live rows per shard = row sums);
    ``key_idx``: positions of the key columns in ``flat``; ``lut``
    (bucket -> dst shard) maps hashed bucket ids when the caller
    buckets first (aggregates), None hashes straight to shards.
    Returns (staged cols, per-dest counts, compressed bytes)."""
    host = []
    for v, val in flat:
        hv = np.asarray(v)
        hm = np.asarray(val) if val is not None else \
            np.ones(hv.shape[0], dtype=bool)
        host.append((hv, hm))
    counts = np.asarray(hist).sum(axis=1).astype(np.int64)
    # hash parity with the device kernels: validity participates only
    # where the trace saw one (None hashes as always-live)
    keys = [(host[i][0], host[i][1] if flat[i][1] is not None else None)
            for i in key_idx]
    bids = host_hash_partition_ids(keys, num_buckets)
    pids = bids if lut is None else np.asarray(lut, dtype=np.int32)[bids]
    # hedge eligibility: staging is PURE host-side work (no collective,
    # no device state), so when the exchange spans a SUSPECT host the
    # repartition may be re-dispatched on the healthy path and the
    # first result wins (robustness/grayfailure.py hedged_call; a plain
    # call when gray failure is off or every host is healthy)
    from spark_rapids_tpu.robustness import grayfailure
    try:
        from spark_rapids_tpu.api.session import TpuSession
        session = TpuSession._active
    except ImportError:
        session = None
    suspect = grayfailure.suspect_host_in(
        session, getattr(session, "mesh", None))
    return grayfailure.hedged_call(
        session, "exchange.host_staging", suspect,
        lambda: host_staged_partition(host, counts, pids, nshards))
