"""spark_rapids_tpu — a TPU-native columnar SQL/ETL engine.

A ground-up re-design of the RAPIDS Accelerator for Apache Spark
(reference: /root/reference, open-infrastructure-labs/spark-rapids) for TPU
hardware.  Where the reference lowers Spark physical plans to libcudf kernels
called one JNI hop at a time, this framework compiles an entire query stage
(scan -> filter -> project -> partial aggregate) into a single XLA computation
over fixed-capacity columnar buffers, and expresses shuffle as a pod-wide
``shard_map`` all-to-all collective over ICI instead of UCX point-to-point
transfers.

Layer map (mirrors SURVEY.md section 1 of the reference analysis):

========  ==============================================  =======================
Layer     This package                                    Reference counterpart
========  ==============================================  =======================
L0        XLA / Pallas kernels (``ops/``)                 libcudf + JNI
L1        ``memory/`` spill catalog, stores, semaphore    RMM + RapidsBufferStore
L2        ``config/`` typed conf registry                 RapidsConf.scala
L3        ``plan/`` meta/tagging planner + overrides      GpuOverrides/RapidsMeta
L4        ``exec/`` columnar physical operators           GpuExec subclasses
L5        ``parallel/`` mesh shuffle & broadcast          shuffle-plugin (UCX)
L6        ``io/`` parquet/orc/csv scan & write            GpuParquetScan etc.
L7        ``udf/`` Python-bytecode -> expression compiler udf-compiler (Scala)
L9        ``tools/`` qualification & profiling CLIs       tools/
========  ==============================================  =======================
"""

import jax as _jax

# Spark SQL semantics require 64-bit longs and doubles end to end; JAX
# defaults to 32-bit. Enabled at engine import, before any tracing.
_jax.config.update("jax_enable_x64", True)

from spark_rapids_tpu.version import __version__

from spark_rapids_tpu.columnar.dtypes import (
    DataType, BOOL, INT8, INT16, INT32, INT64, FLOAT32, FLOAT64,
    STRING, DATE32, TIMESTAMP_US, DecimalType,
)
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.config.rapids_conf import RapidsConf

__all__ = [
    "__version__",
    "DataType", "BOOL", "INT8", "INT16", "INT32", "INT64", "FLOAT32",
    "FLOAT64", "STRING", "DATE32", "TIMESTAMP_US", "DecimalType",
    "Column", "ColumnarBatch", "RapidsConf",
]
