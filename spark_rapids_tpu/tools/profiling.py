"""Profiling tool: aggregate per-op metrics, plan graphs, health checks.

CLI over engine event logs — the role of the reference's profiling tool
(tools/src/main/.../profiling/ProfileMain.scala: CollectInformation,
Analysis, HealthCheck, GenerateDot): per-operator time/row aggregation
across queries, the slowest queries, spill totals, query-duration skew,
a DOT graph of any query's physical plan, and a health check listing
failures.

Usage:  python -m spark_rapids_tpu.tools.profiling LOGDIR
            [--dot QUERYID] [--top N]
"""

from __future__ import annotations

import argparse
import statistics
import sys
from collections import defaultdict
from typing import Dict, List, Tuple

from spark_rapids_tpu.tools.eventlog import AppInfo, QueryInfo, load_logs


def aggregate_ops(apps: List[AppInfo]) -> List[Tuple[str, float, int, int]]:
    """[(op_name, total opTime ms, total rows, occurrences)] sorted by
    time desc."""
    time_ns: Dict[str, int] = defaultdict(int)
    rows: Dict[str, int] = defaultdict(int)
    count: Dict[str, int] = defaultdict(int)
    for app in apps:
        for q in app.queries:
            for path, m in q.metrics.items():
                name = path.rsplit(".", 1)[-1]
                time_ns[name] += m.get("opTimeSelf", m.get("opTime", 0))
                rows[name] += m.get("numOutputRows", 0)
                count[name] += 1
    out = [(n, time_ns[n] / 1e6, rows[n], count[n]) for n in time_ns]
    out.sort(key=lambda t: -t[1])
    return out


def slowest_queries(apps: List[AppInfo], top: int
                    ) -> List[Tuple[str, QueryInfo]]:
    pairs = [(a.session_id, q) for a in apps for q in a.queries]
    pairs.sort(key=lambda p: -p[1].duration_ms)
    return pairs[:top]


def skew_stats(apps: List[AppInfo]) -> Dict[str, float]:
    durs = [q.duration_ms for a in apps for q in a.queries if q.succeeded]
    if not durs:
        return {}
    mean = statistics.fmean(durs)
    return {
        "queries": len(durs),
        "mean_ms": mean,
        "p50_ms": statistics.median(durs),
        "max_ms": max(durs),
        "skew_ratio": (max(durs) / mean) if mean else 0.0,
    }


def health_check(apps: List[AppInfo]) -> List[str]:
    problems = []
    for a in apps:
        for q in a.queries:
            if not q.succeeded:
                problems.append(
                    f"{a.session_id} query {q.query_id}: {q.status}")
            spilled = sum(q.spill.values()) if q.spill else 0
            if spilled:
                problems.append(
                    f"{a.session_id} query {q.query_id}: spilled "
                    f"{spilled} bytes")
            retries = q.retry.get("retryCount", 0) if q.retry else 0
            splits = q.retry.get("splitAndRetryCount", 0) if q.retry else 0
            if retries or splits:
                problems.append(
                    f"{a.session_id} query {q.query_id}: device OOM "
                    f"recovered — {retries} retries, {splits} splits")
    return problems


def plan_dot(q: QueryInfo) -> str:
    """Physical plan as a DOT digraph (GenerateDot.scala analog)."""
    lines = q.physical_plan.splitlines()
    out = ["digraph plan {", "  rankdir=BT;",
           '  node [shape=box, fontname="monospace"];']
    # indentation encodes the tree
    stack: List[Tuple[int, int]] = []  # (depth, node_id)
    for i, raw in enumerate(lines):
        depth = (len(raw) - len(raw.lstrip())) // 2
        label = raw.strip().replace('"', r'\"')
        out.append(f'  n{i} [label="{label}"];')
        while stack and stack[-1][0] >= depth:
            stack.pop()
        if stack:
            out.append(f"  n{i} -> n{stack[-1][1]};")
        stack.append((depth, i))
    out.append("}")
    return "\n".join(out)


def format_report(apps: List[AppInfo], top: int) -> str:
    out = ["=" * 72, "TPU Profiling Report", "=" * 72]
    out.append(f"\nSessions: {len(apps)}, queries: "
               f"{sum(len(a.queries) for a in apps)}")
    out.append("\n-- Operator aggregate (by total opTime) --")
    out.append(f"{'operator':40s} {'time_ms':>10s} {'rows':>12s} "
               f"{'uses':>6s}")
    for name, ms, rows, n in aggregate_ops(apps)[:top]:
        out.append(f"{name:40s} {ms:10.2f} {rows:12d} {n:6d}")
    out.append("\n-- Slowest queries --")
    for sid, q in slowest_queries(apps, top):
        out.append(f"  {sid} q{q.query_id}: {q.duration_ms:.1f} ms "
                   f"[{q.status}]")
    sk = skew_stats(apps)
    if sk:
        out.append("\n-- Duration distribution --")
        out.append(f"  n={sk['queries']} mean={sk['mean_ms']:.1f}ms "
                   f"p50={sk['p50_ms']:.1f}ms max={sk['max_ms']:.1f}ms "
                   f"skew={sk['skew_ratio']:.2f}x")
    problems = health_check(apps)
    out.append("\n-- Health check --")
    if problems:
        out.extend(f"  ! {p}" for p in problems)
    else:
        out.append("  no failures, no spill")
    return "\n".join(out)


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="spark_rapids_tpu.tools.profiling", description=__doc__)
    ap.add_argument("logdir")
    ap.add_argument("--top", type=int, default=10)
    ap.add_argument("--dot", type=int, default=None, metavar="QUERYID",
                    help="print a DOT graph of this query's physical plan")
    args = ap.parse_args(argv)
    apps = load_logs(args.logdir)
    if not apps:
        print("no event logs found", file=sys.stderr)
        return 1
    if args.dot is not None:
        for a in apps:
            for q in a.queries:
                if q.query_id == args.dot:
                    print(plan_dot(q))
                    return 0
        print(f"query {args.dot} not found", file=sys.stderr)
        return 1
    print(format_report(apps, args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
