"""Profiling tool: aggregate per-op metrics, plan graphs, health checks.

CLI over engine event logs — the role of the reference's profiling tool
(tools/src/main/.../profiling/ProfileMain.scala: CollectInformation,
Analysis, HealthCheck, GenerateDot): per-operator time/row aggregation
across queries, the slowest queries, spill totals, query-duration skew,
a DOT graph of any query's physical plan, and a health check listing
failures.

Usage:  python -m spark_rapids_tpu.tools.profiling LOGDIR
            [--dot QUERYID] [--top N]
"""

from __future__ import annotations

import argparse
import statistics
import sys
from collections import defaultdict
from typing import Dict, List, Tuple

from spark_rapids_tpu.tools.eventlog import AppInfo, QueryInfo, load_logs


def aggregate_ops(apps: List[AppInfo]) -> List[Tuple[str, float, int, int]]:
    """[(op_name, total opTime ms, total rows, occurrences)] sorted by
    time desc."""
    time_ns: Dict[str, int] = defaultdict(int)
    rows: Dict[str, int] = defaultdict(int)
    count: Dict[str, int] = defaultdict(int)
    for app in apps:
        for q in app.queries:
            for path, m in q.metrics.items():
                name = path.rsplit(".", 1)[-1]
                time_ns[name] += m.get("opTimeSelf", m.get("opTime", 0))
                rows[name] += m.get("numOutputRows", 0)
                count[name] += 1
    out = [(n, time_ns[n] / 1e6, rows[n], count[n]) for n in time_ns]
    out.sort(key=lambda t: -t[1])
    return out


def slowest_queries(apps: List[AppInfo], top: int
                    ) -> List[Tuple[str, QueryInfo]]:
    pairs = [(a.session_id, q) for a in apps for q in a.queries]
    pairs.sort(key=lambda p: -p[1].duration_ms)
    return pairs[:top]


def skew_stats(apps: List[AppInfo]) -> Dict[str, float]:
    durs = [q.duration_ms for a in apps for q in a.queries if q.succeeded]
    if not durs:
        return {}
    mean = statistics.fmean(durs)
    return {
        "queries": len(durs),
        "mean_ms": mean,
        "p50_ms": statistics.median(durs),
        "max_ms": max(durs),
        "skew_ratio": (max(durs) / mean) if mean else 0.0,
    }


def pipeline_stats(apps: List[AppInfo]) -> Dict[str, float]:
    """Aggregate async-pipeline effectiveness across queries: mean fill
    ratio (batch-weighted), total host syncs, overlap time, and jit
    cache hit rate (ops/jit_cache.py counters)."""
    fill_w, batches, syncs, overlap_ms = 0.0, 0, 0, 0.0
    hits, misses, piped = 0, 0, 0
    for a in apps:
        for q in a.queries:
            p = q.pipeline
            if not p:
                continue
            piped += 1
            b = p.get("batches", 0)
            fill_w += p.get("pipelineFillRatio", 0.0) * b
            batches += b
            syncs += p.get("hostSyncCount", 0)
            overlap_ms += p.get("uploadOverlapMs", 0.0)
            hits += p.get("jitCacheHits", 0)
            misses += p.get("jitCacheMisses", 0)
    if not piped:
        return {}
    return {
        "queries": piped,
        "batches": batches,
        "fill_ratio": (fill_w / batches) if batches else 0.0,
        "host_sync_count": syncs,
        "upload_overlap_ms": overlap_ms,
        "jit_cache_hits": hits,
        "jit_cache_misses": misses,
    }


def shuffle_wire_stats(apps: List[AppInfo]) -> Dict[str, float]:
    """Aggregate shuffle-wire effectiveness across distributed queries:
    exchanges, collectives launched, bytes moved and the overall
    padding ratio (wire rows / useful rows — 1.0 is a perfectly dense
    exchange; numShards is full-capacity padding)."""
    exchanged, exch, coll, moved, useful, bytes_, ovf, fb = \
        0, 0, 0, 0, 0, 0, 0, 0
    overlap_ms, wall_ms, async_n, ragged_n, staged_b = 0.0, 0.0, 0, 0, 0
    enc_saved, dict_b, enc_decoded, dict_fb = 0, 0, 0, 0
    for a in apps:
        for q in a.queries:
            s = q.shuffle
            if not s or not s.get("exchanges"):
                continue
            exchanged += 1
            exch += s.get("exchanges", 0)
            coll += s.get("collectives", 0)
            moved += s.get("rowsMoved", 0)
            useful += s.get("rowsUseful", 0)
            bytes_ += s.get("bytesMoved", 0)
            ovf += s.get("slotOverflowRetries", 0)
            fb += s.get("perColumnFallbacks", 0)
            overlap_ms += s.get("exchangeOverlapMs", 0.0)
            wall_ms += s.get("exchangeWallMs", 0.0)
            async_n += s.get("asyncExchanges", 0)
            ragged_n += s.get("raggedExchanges", 0)
            staged_b += s.get("hostStagedBytes", 0)
            enc_saved += s.get("encodedBytesSaved", 0)
            dict_b += s.get("wireDictBytes", 0)
            enc_decoded += s.get("encodableDecodedExchanges", 0)
            dict_fb += s.get("wireDictFallbacks", 0)
    if not exchanged:
        return {}
    return {
        "queries": exchanged,
        "exchanges": exch,
        "collectives": coll,
        "bytes_moved": bytes_,
        "padding_ratio": moved / max(useful, 1),
        "slot_overflow_retries": ovf,
        "per_column_fallbacks": fb,
        # compressed wire (encoding.wire.enabled): bytes the code
        # narrowing shaved plus the dictionary-delta broadcast cost
        "encoded_bytes_saved": enc_saved,
        "wire_dict_bytes": dict_b,
        "wire_dict_fallbacks": dict_fb,
        "encodable_decoded_exchanges": enc_decoded,
        # async exchange/compute overlap (parallel/exchange_async.py):
        # overlap_fraction is the headline — how much of the exchange
        # tail the host spent dispatching downstream work instead of
        # blocking on verification
        "exchange_overlap_ms": round(overlap_ms, 3),
        "exchange_wall_ms": round(wall_ms, 3),
        "overlap_fraction": round(overlap_ms / wall_ms, 3)
        if wall_ms else 0.0,
        "async_exchanges": async_n,
        "ragged_exchanges": ragged_n,
        "host_staged_bytes": staged_b,
    }


def checkpoint_stats(apps: List[AppInfo]) -> Dict[str, float]:
    """Aggregate stage-checkpoint effectiveness across queries: writes
    and bytes persisted, resumes and the exchange stages they skipped,
    evictions and invalidations (robustness/checkpoint.py)."""
    writes = bytes_ = resumes = skipped = evicts = invalid = 0
    touched = 0
    for a in apps:
        events = list(a.checkpoint) + [c for q in a.queries
                                       for c in q.checkpoint]
        if not events:
            continue
        touched += 1
        for c in events:
            kind = c.get("kind")
            if kind == "write":
                writes += 1
                bytes_ += c.get("bytes", 0)
            elif kind == "resume":
                resumes += 1
                skipped += c.get("stagesSaved", 0)
            elif kind == "evict":
                evicts += 1
            elif kind == "invalid":
                invalid += 1
    if not touched:
        return {}
    return {
        "writes": writes,
        "bytes_written": bytes_,
        "resumes": resumes,
        "stages_skipped": skipped,
        "evictions": evicts,
        "invalidations": invalid,
    }


def incremental_stats(apps: List[AppInfo]) -> Dict[str, object]:
    """Continuous-ingest effectiveness across sessions
    (robustness/incremental.py): committed epochs split by mode
    (incremental vs full-recompute), rollbacks, state evictions,
    lineage-splice resumes, and the standing state's last committed
    size.  ``reuse_ratio`` is the headline: the fraction of ticks that
    actually rode the committed epoch instead of recomputing."""
    commits = inc = full = rollbacks = evicts = resumes = 0
    state_bytes = 0
    watermarks: Dict[object, int] = {}  # per standing query (store id)
    wm_buckets = wm_bytes = 0
    sink_commits = sink_replays = 0
    rounds = round_pulls = round_splices = round_failures = 0
    for a in apps:
        events = list(a.incremental) + [e for q in a.queries
                                        for e in q.incremental]
        for e in events:
            kind = e.get("kind")
            if kind == "commit":
                commits += 1
                if e.get("mode") == "incremental" or e.get("reusedState"):
                    inc += 1
                else:
                    full += 1
                state_bytes = e.get("stateBytes", state_bytes)
            elif kind == "rollback":
                rollbacks += 1
            elif kind == "evict":
                evicts += 1
            elif kind == "resume":
                resumes += 1
            elif kind == "watermark":
                if e.get("watermark") is not None:
                    watermarks[e.get("store")] = e["watermark"]
                wm_buckets += e.get("evictedBuckets", 0)
                wm_bytes += e.get("evictedBytes", 0)
            elif kind == "sink":
                if e.get("replayed"):
                    sink_replays += 1
                else:
                    sink_commits += 1
            elif kind == "round":
                rounds += 1
                round_pulls += e.get("sourcePulls", 0)
                round_splices += e.get("splices", 0)
                round_failures += e.get("failures", 0)
    if not commits and not rollbacks and not rounds:
        return {}
    return {
        "commits": commits,
        "incremental_ticks": inc,
        "full_recomputes": full,
        "rollbacks": rollbacks,
        "state_evictions": evicts,
        "splice_resumes": resumes,
        "state_bytes": state_bytes,
        "reuse_ratio": inc / commits if commits else 0.0,
        # windowed shapes: where each standing query's event-time
        # watermark last landed ({store id: watermark} — one pooled
        # number would show whichever query committed last) and what
        # eviction reclaimed across all committed epochs
        "watermark": watermarks or None,
        "watermark_evicted_buckets": wm_buckets,
        "watermark_evicted_bytes": wm_bytes,
        # exactly-once sinks: NEW committed emissions vs idempotent
        # re-emissions of an already-committed epoch
        "sink_commits": sink_commits,
        "sink_replays": sink_replays,
        # fleet rounds: shared-ingest fan-out effectiveness
        "fleet_rounds": rounds,
        "fleet_source_pulls": round_pulls,
        "fleet_splices": round_splices,
        "fleet_failures": round_failures,
    }


def sharing_stats(apps: List[AppInfo]) -> Dict[str, float]:
    """Cross-query reuse effectiveness across sessions
    (serving/reuse.py + serving/scheduler.py): result-cache
    hits/misses/stores/invalidations, shared stage-store
    writes/splices, and the fair interleaver's wait/timeslice
    accounting.  ``result_cache_hits`` and ``stage_splices`` are the
    headline numbers the bench --concurrency overlap mode reports."""
    hits = misses = stores = invalid = evicts = 0
    t_hits = t_misses = t_stores = 0
    writes = splices = 0
    interleaved = 0
    wait_ms = slices = 0.0
    for a in apps:
        events = list(a.sharing_events) + \
            [e for q in a.queries for e in q.sharing_events]
        for e in events:
            kind, store = e.get("kind"), e.get("store")
            if store == "result":
                if kind == "hit":
                    hits += 1
                elif kind == "store":
                    stores += 1
                elif kind == "invalid":
                    invalid += 1
                elif kind == "evict":
                    evicts += 1
            elif store == "template":
                if kind == "hit":
                    t_hits += 1
                elif kind == "store":
                    t_stores += 1
            else:
                if kind == "write":
                    writes += 1
                elif kind == "splice":
                    splices += 1
                elif kind == "invalid":
                    invalid += 1
                elif kind == "evict":
                    evicts += 1
        for q in a.queries:
            sh = q.sharing
            if not sh:
                continue
            if sh.get("resultCache") == "miss" or \
                    sh.get("resultCache") == "invalidated":
                misses += 1
            if sh.get("templateCache") == "miss" or \
                    sh.get("templateCache") == "invalidated":
                t_misses += 1
            il = sh.get("interleave")
            if il:
                interleaved += 1
                wait_ms += il.get("waitMs", 0.0)
                slices += il.get("timeslices", 0)
    if not (hits or misses or stores or writes or splices or
            interleaved or invalid or evicts or
            t_hits or t_misses or t_stores):
        return {}
    return {
        "result_cache_hits": hits,
        "result_cache_misses": misses,
        "result_cache_stores": stores,
        "template_cache_hits": t_hits,
        "template_cache_misses": t_misses,
        "template_cache_stores": t_stores,
        "stage_writes": writes,
        "stage_splices": splices,
        "invalidations": invalid,
        "evictions": evicts,
        "interleaved_queries": interleaved,
        "interleave_wait_ms": wait_ms,
        "timeslices": slices,
    }


def planner_stats(apps: List[AppInfo]) -> Dict[str, object]:
    """Self-tuning cost-model effectiveness across queries
    (plan/costmodel.py QueryEnd ``planner`` dicts): decisions per
    knob, how many were evidence-fed vs built-in vs conf-overridden,
    plus the replan/mispredict/degraded-load tallies the health
    checks key on.  Empty when no query carried a planner dict
    (costModel.enabled off)."""
    queries = decisions = evidence = overrides = 0
    replans = mispredicts = 0
    invalid = 0
    by_knob: Dict[str, int] = {}
    chosen: Dict[str, int] = {}
    for a in apps:
        invalid += len(a.costmodel)
        for q in a.queries:
            invalid += len(q.costmodel)
            p = q.planner
            if not p:
                continue
            queries += 1
            replans += int(p.get("replans", 0))
            mispredicts += int(p.get("mispredicts", 0))
            for d in p.get("decisions", []):
                decisions += 1
                by_knob[d.get("knob", "?")] = \
                    by_knob.get(d.get("knob", "?"), 0) + 1
                if d.get("knob") == "exchange":
                    chosen[d.get("chosen", "?")] = \
                        chosen.get(d.get("chosen", "?"), 0) + 1
                if d.get("evidence"):
                    evidence += 1
                if d.get("override"):
                    overrides += 1
    if not queries and not invalid:
        return {}
    return {
        "queries": queries,
        "decisions": decisions,
        "evidence_decisions": evidence,
        "override_decisions": overrides,
        "by_knob": dict(sorted(by_knob.items())),
        "exchange_modes": dict(sorted(chosen.items())),
        "replans": replans,
        "mispredicts": mispredicts,
        "invalid_loads": invalid,
    }


def fusion_stats(apps: List[AppInfo]) -> Dict[str, float]:
    """Whole-stage fusion + persistent jit-cache effectiveness across
    queries (exec/fusion.py, ops/jit_cache.py): stages/operators fused,
    jit dispatches saved, chains that COULD have fused but ran unfused,
    and the persistent tier's warm-start hit rate."""
    touched = stages = ops = saved = chains = encoded = 0
    phits = pmisses = pinvalid = pstores = 0
    for a in apps:
        for q in a.queries:
            fu = q.fusion
            if not fu:
                continue
            touched += 1
            stages += fu.get("fusedStages", 0)
            ops += fu.get("fusedOperators", 0)
            saved += fu.get("dispatchesSaved", 0)
            chains += fu.get("fusibleChains", 0)
            encoded += fu.get("encodedStages", 0)
            phits += fu.get("persistentHits", 0)
            pmisses += fu.get("persistentMisses", 0)
            pinvalid += fu.get("persistentInvalid", 0)
            pstores += fu.get("persistentStores", 0)
    if not touched:
        return {}
    return {
        "queries": touched,
        "fused_stages": stages,
        "fused_operators": ops,
        "dispatches_saved": saved,
        "fusible_chains": chains,
        "encoded_stages": encoded,
        "persistent_hits": phits,
        "persistent_misses": pmisses,
        "persistent_invalid": pinvalid,
        "persistent_stores": pstores,
    }


def span_stats(apps: List[AppInfo]) -> Dict[str, object]:
    """"Where the time went": aggregate the span rollups (QueryEnd
    ``spans`` dicts, utils/tracing.py) across traced queries — wall vs
    attributed exclusive time, the phase stripes, and the top span
    points by exclusive time.  ``unattributed_frac`` is the headline
    health metric: wall the taxonomy never covered."""
    traced = 0
    wall = excl = unattr = overlap = 0.0
    phases: Dict[str, float] = defaultdict(float)
    points: Dict[str, float] = defaultdict(float)
    for a in apps:
        for q in a.queries:
            sp = q.spans
            if not sp or not sp.get("events"):
                continue
            traced += 1
            wall += sp.get("wallMs", 0.0)
            excl += sp.get("exclusiveMs", 0.0)
            unattr += sp.get("unattributedMs", 0.0)
            overlap += sp.get("overlapMs", 0.0)
            for ph, ms in (sp.get("phases") or {}).items():
                phases[ph] += ms
            for pt, v in (sp.get("points") or {}).items():
                points[pt] += v.get("exclusiveMs", 0.0)
    if not traced:
        return {}
    return {
        "queries": traced,
        "wall_ms": round(wall, 3),
        "exclusive_ms": round(excl, 3),
        "unattributed_ms": round(unattr, 3),
        "unattributed_frac": round(unattr / wall, 4) if wall else 0.0,
        "overlap_ms": round(overlap, 3),
        "phases": {k: round(v, 3) for k, v in sorted(phases.items())},
        "top_points": sorted(points.items(), key=lambda kv: -kv[1]),
    }


# a query whose spans cover less than this fraction of its wall is an
# instrumentation blind spot — the health check that keeps future
# instrumentation honest (ISSUE 12 contract: wall - sum(exclusive)
# > 20% flags)
UNATTRIBUTED_FRAC_LIMIT = 0.20
# ignore sub-5ms envelopes: fixed per-query overheads (planning,
# envelope bookkeeping) legitimately dominate trivial queries
_UNATTRIBUTED_MIN_WALL_MS = 5.0


def site_history(obs_dir: str, top: int = 20) -> str:
    """Per-site observation history (utils/tracing.ObservationStore):
    the persisted evidence the self-tuning planner will consume —
    rendered so a human can consume it first."""
    from spark_rapids_tpu.utils.tracing import ObservationStore
    records = ObservationStore.read(obs_dir)
    if not records:
        return f"no observation store under {obs_dir}"
    out = [f"-- Per-site observation history ({obs_dir}) --",
           f"{'site':18s} {'n':>5s} {'rows':>10s} {'bytes':>12s} "
           f"{'skew':>6s} {'compile_ms':>10s} {'overlap_ms':>10s} "
           f"{'span_ms':>9s}"]
    ranked = sorted(records.items(),
                    key=lambda kv: -kv[1].get("span_ms", 0.0))
    for sid, r in ranked[:top]:
        out.append(
            f"{sid:18s} {int(r.get('n', 0)):5d} "
            f"{int(r.get('rows', 0)):10d} {int(r.get('bytes', 0)):12d} "
            f"{r.get('skew', 0.0):6.3f} {r.get('compile_ms', 0.0):10.1f} "
            f"{r.get('overlap_ms', 0.0):10.1f} "
            f"{r.get('span_ms', 0.0):9.1f}")
    if len(ranked) > top:
        out.append(f"  ... {len(ranked) - top} more site(s)")
    return "\n".join(out)


def nearest_rank(sorted_vals: List[float], p: float) -> float:
    """Nearest-rank percentile over an ascending list — shared by the
    concurrency report and ``bench.py --concurrency`` so the two can
    never silently diverge."""
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(int(p * len(sorted_vals)),
                           len(sorted_vals) - 1)]


def concurrency_stats(apps: List[AppInfo]) -> Dict[str, float]:
    """Serving-layer concurrency report: peak simultaneously-open
    query envelopes per the event timeline, admission grants/waits and
    typed rejections, and budget-ladder activity — the observability
    face of the admission semaphore (serving/admission.py)."""
    grants = rejects = budget_events = 0
    wait_ms = 0.0
    waits: List[float] = []
    peak = 0
    for a in apps:
        peak = max(peak, a.max_concurrent())
        grants += len(a.admission)
        rejects += len(a.rejections)
        budget_events += len(a.budget)
        # one wait sample per admitted query: the grant events are the
        # complete population (every admission emits one, whether or
        # not the query later reaches QueryEnd); the per-query
        # QueryEnd dicts restate the same waits, so counting both
        # would double the percentile multiset
        for g in a.admission:
            w = g.get("waitMs", 0.0)
            wait_ms += w
            waits.append(w)
        if not a.admission:
            for q in a.queries:
                if q.admission:
                    w = q.admission.get("waitMs", 0.0)
                    wait_ms += w
                    waits.append(w)
        for q in a.queries:
            budget_events += len(q.budget)
    if not grants and not rejects and peak <= 1:
        return {}
    waits.sort()
    return {
        "max_concurrent": peak,
        "admitted": grants,
        "rejected": rejects,
        "total_wait_ms": round(wait_ms, 3),
        "p50_wait_ms": round(nearest_rank(waits, 0.50), 3),
        "p95_wait_ms": round(nearest_rank(waits, 0.95), 3),
        "budget_events": budget_events,
    }


def fleet_stats(apps: List[AppInfo]) -> Dict[str, object]:
    """Fleet membership report: host joins/losses, mesh shrink
    actions, and cache-fence activity (bumps and the rejected stale
    publishes the fence exists to stop) — the observability face of
    the multi-host machinery (parallel/mesh.py, serving/fleetcache.py)."""
    joins = losses = shrinks = bumps = rejections = 0
    cross_hits = 0
    suspects = recoveries = quarantines = rejoins = 0
    hedges_fired = hedges_won = dup_suppressed = 0
    hosts: set = set()
    lost_hosts: set = set()
    for a in apps:
        for ev in a.fleet:
            kind = ev.get("kind")
            if kind == "join":
                joins += 1
                hosts.add((a.session_id, ev.get("host")))
            elif kind == "loss":
                losses += 1
                lost_hosts.add((a.session_id, ev.get("host")))
            elif kind == "shrink":
                shrinks += 1
            elif kind == "fence":
                if ev.get("action") == "bump":
                    bumps += 1
                elif ev.get("action") == "reject":
                    rejections += 1
            elif kind == "suspect":
                suspects += 1
            elif kind == "recovered":
                recoveries += 1
            elif kind == "quarantine":
                quarantines += 1
            elif kind == "rejoin":
                rejoins += 1
            elif kind == "hedge_fired":
                hedges_fired += 1
            elif kind == "hedge_won":
                hedges_won += 1
        for q in a.queries:
            fh = getattr(q, "fleet_health", {}) or {}
            dup_suppressed += int(fh.get("duplicatesSuppressed", 0))
        for q in a.queries:
            for e in q.sharing_events:
                if e.get("kind") in ("hit", "splice") and \
                        e.get("tier") == "fleet" and \
                        e.get("crossProcess"):
                    cross_hits += 1
        for e in a.sharing_events:
            if e.get("kind") in ("hit", "splice") and \
                    e.get("tier") == "fleet" and e.get("crossProcess"):
                cross_hits += 1
    if not (joins or losses or shrinks or bumps or rejections
            or suspects or hedges_fired or quarantines or rejoins):
        return {}
    return {
        "hosts_seen": len(hosts),
        "joins": joins,
        "losses": losses,
        "hosts_lost": len(lost_hosts),
        "mesh_shrinks": shrinks,
        "fence_bumps": bumps,
        "fenced_publishes": rejections,
        "fleet_cross_hits": cross_hits,
        "suspects": suspects,
        "suspect_recoveries": recoveries,
        "quarantines": quarantines,
        "rejoins": rejoins,
        "hedges_fired": hedges_fired,
        "hedges_won": hedges_won,
        "duplicates_suppressed": dup_suppressed,
    }


def _fleet_problems(a: AppInfo) -> List[str]:
    """Fleet health: flapping hosts (lost then re-joined — a network
    or heartbeat-tuning problem, each flap pays a shrink/recovery),
    shrink rungs that saved nothing (the query fell through to cpu
    anyway, so the fleet paid the mesh rebuild for nothing), and
    fenced writers (the zombie-protection WORKING — worth surfacing
    because a zombie process is still running somewhere)."""
    problems: List[str] = []
    who = a.session_id
    loss_hosts: Dict[object, int] = {}
    join_after_loss: Dict[object, int] = {}
    for ev in a.fleet:
        h = ev.get("host")
        if ev.get("kind") == "loss":
            loss_hosts[h] = loss_hosts.get(h, 0) + 1
        elif ev.get("kind") == "join" and h in loss_hosts:
            join_after_loss[h] = join_after_loss.get(h, 0) + 1
    for h, flaps in sorted(join_after_loss.items()):
        problems.append(
            f"{who}: host {h} FLAPPING — declared lost then re-joined "
            f"{flaps}x; each flap pays a mesh shrink + recovery "
            "re-drive. Raise fleet.heartbeatMs/missedBeatsFatal or "
            "fix the host's network before it erodes the fleet")
    shrinks = [ev for ev in a.fleet if ev.get("kind") == "shrink"]
    if shrinks:
        # a shrink that saved nothing: some query still fell through
        # to the cpu rung (or died) after the mesh rebuild
        wasted = 0
        for q in a.queries:
            rungs = [r.get("rung") or r.get("action")
                     for r in q.recovery]
            if any(r == "shrink" for r in rungs) and (
                    any(r == "cpu" for r in rungs) or not q.succeeded):
                wasted += 1
        if wasted:
            problems.append(
                f"{who}: shrink rung saved nothing for {wasted} "
                "quer(y/ies) — the survivor mesh was rebuilt but the "
                "re-drive still fell to cpu (or failed); if this "
                "repeats, the failing stage doesn't fit the shrunken "
                "fleet and the ladder should skip straight to cpu")
    fenced = [ev for ev in a.fleet if ev.get("kind") == "fence"
              and ev.get("action") == "reject"]
    if fenced:
        eps = sorted({(ev.get("writerEpoch"), ev.get("fenceEpoch"))
                      for ev in fenced})
        problems.append(
            f"{who}: {len(fenced)} stale fleet-cache publish(es) "
            f"REJECTED by the fence (writer/fence epochs: "
            f"{', '.join(f'{w}<{f}' for w, f in eps)}) — the "
            "zombie-writer protection worked and no reader saw the "
            "entry, but a fenced-out process is still running "
            "somewhere; make sure the lost host actually died")
    # gray-failure checks: a SUSPECT verdict that never led anywhere
    # (no hedge, no quarantine, no recovery — detection without
    # mitigation is just latency), and hedges that never won (the
    # duplicate work bought nothing — the deadline fires too early or
    # the "healthy" path is just as slow)
    suspect_hosts = {ev.get("host") for ev in a.fleet
                     if ev.get("kind") == "suspect"}
    mitigated = {ev.get("host") for ev in a.fleet
                 if ev.get("kind") in ("quarantine", "recovered",
                                       "rejoin", "hedge_fired",
                                       "hedge_won")}
    for h in sorted(h for h in suspect_hosts
                    if h not in mitigated and h is not None):
        problems.append(
            f"{who}: host {h} went SUSPECT but was never mitigated — "
            "no hedge fired, no quarantine, no recovery; the fleet "
            "kept waiting on the slow host. Lower "
            "fleet.quarantineAfterMs or check the hedge-eligible "
            "paths actually ran")
    fired = sum(1 for ev in a.fleet if ev.get("kind") == "hedge_fired")
    won = sum(1 for ev in a.fleet if ev.get("kind") == "hedge_won")
    if fired and not won:
        problems.append(
            f"{who}: {fired} hedge(s) fired but ZERO won — the "
            "primary always beat the re-dispatch, so the hedging cost "
            "bought nothing; raise fleet.hedgeMarginFactor/"
            "hedgePercentile so hedges fire only on real stalls")
    return problems


def health_check(apps: List[AppInfo]) -> List[str]:
    problems = []
    for a in apps:
        for q in a.queries:
            if not q.succeeded:
                problems.append(
                    f"{a.session_id} query {q.query_id}: {q.status}")
            p = q.pipeline
            if p and p.get("batches", 0) >= 4 and \
                    p.get("pipelineFillRatio", 1.0) < 0.25:
                problems.append(
                    f"{a.session_id} query {q.query_id}: pipeline "
                    f"starved (fill ratio "
                    f"{p['pipelineFillRatio']:.2f} over "
                    f"{p['batches']} batches) — the producer is the "
                    "bottleneck; check reader threads / host decode")
            if p and p.get("batches", 0) > 0 and \
                    p.get("hostSyncCount", 0) > 4 * p["batches"]:
                problems.append(
                    f"{a.session_id} query {q.query_id}: "
                    f"{p['hostSyncCount']} host syncs over "
                    f"{p['batches']} batches — per-batch device->host "
                    "round trips serialize the pipeline "
                    "(docs/performance.md sync-point discipline)")
            sh = q.shuffle
            if sh and sh.get("exchanges"):
                pr = sh.get("paddingRatio", 0.0)
                if pr > 4.0:
                    problems.append(
                        f"{a.session_id} query {q.query_id}: shuffle "
                        f"padding ratio {pr:.1f}x over "
                        f"{sh.get('exchanges', 0)} exchange(s) — most "
                        "ICI bytes are padding; the slot planner is "
                        "oversizing (skewed partitions, or slot.mode="
                        "capacity left on)")
                if sh.get("perColumnFallbacks", 0):
                    problems.append(
                        f"{a.session_id} query {q.query_id}: "
                        f"{sh['perColumnFallbacks']} exchange(s) fell "
                        "back to per-column collectives — an unpackable "
                        "column or packed.enabled=false defeats the "
                        "fused shuffle wire format")
                if sh.get("encodableDecodedExchanges", 0):
                    problems.append(
                        f"{a.session_id} query {q.query_id}: "
                        f"{sh['encodableDecodedExchanges']} exchange(s) "
                        "carried dictionary-code columns but shipped "
                        "them DECODED (wide) — enable spark.rapids.tpu"
                        ".encoding.wire.enabled to crush the free "
                        "bytes (docs/performance.md \"Encoded "
                        "execution\")")
                if sh.get("wireDictFallbacks", 0):
                    problems.append(
                        f"{a.session_id} query {q.query_id}: "
                        f"{sh['wireDictFallbacks']} wire dictionary-"
                        "delta broadcast(s) failed verification — the "
                        "launch degraded to the wide wire and the "
                        "dictionary rebroadcasts in full next launch")
                if sh.get("slotOverflowRetries", 0):
                    problems.append(
                        f"{a.session_id} query {q.query_id}: "
                        f"{sh['slotOverflowRetries']} speculative slot "
                        "overflow(s) re-ran at full capacity — data "
                        "skew shifted under a warm exchange site")
            sp = q.spans
            if sp and sp.get("events") and \
                    sp.get("wallMs", 0.0) >= _UNATTRIBUTED_MIN_WALL_MS \
                    and sp.get("unattributedFrac", 0.0) > \
                    UNATTRIBUTED_FRAC_LIMIT:
                problems.append(
                    f"{a.session_id} query {q.query_id}: "
                    f"{sp.get('unattributedMs', 0):.0f}ms of "
                    f"{sp.get('wallMs', 0):.0f}ms wall "
                    f"({sp['unattributedFrac']:.0%}) is UNATTRIBUTED "
                    "by the span taxonomy — an instrumentation blind "
                    "spot; whatever runs there is invisible to every "
                    "perf tool (docs/observability.md)")
            fu = q.fusion
            if fu and fu.get("fusibleChains", 0) > \
                    fu.get("fusedStages", 0):
                lost = fu["fusibleChains"] - fu.get("fusedStages", 0)
                problems.append(
                    f"{a.session_id} query {q.query_id}: {lost} fusible "
                    "operator chain(s) ran UNFUSED — each pays one jit "
                    "dispatch + device materialization per operator per "
                    "batch; check spark.rapids.tpu.fusion.enabled (or "
                    "an unfusible chain member forced the fallback)")
            if fu and fu.get("wireUnfusedLaunches", 0):
                problems.append(
                    f"{a.session_id} query {q.query_id}: "
                    f"{fu['wireUnfusedLaunches']} warm distributed "
                    "stage(s) ran the two-dispatch wire path (compute "
                    "launch + separate pack launch per shard) — "
                    "spark.rapids.tpu.fusion.wire.enabled would fold "
                    "the wire packer into the compute program, one "
                    "launch per shard")
            if fu and fu.get("hashOverflowFallbacks", 0):
                problems.append(
                    f"{a.session_id} query {q.query_id}: "
                    f"{fu['hashOverflowFallbacks']} hash-kernel "
                    "launch(es) overflowed the slot table and re-ran "
                    "the sort kernel — results stay exact, but the "
                    "hash dispatch was wasted work; raise "
                    "spark.rapids.tpu.pallas.hash.tableSlots above "
                    "2x the live key cardinality")
            pl = q.planner
            if pl and pl.get("mispredicts", 0):
                # the SAME factor finish_query counted with — a tuned
                # threshold must not desynchronize the report
                from spark_rapids_tpu.plan.costmodel import \
                    MISPREDICT_FACTOR
                bad = [d for d in pl.get("decisions", [])
                       if d.get("observed") is not None
                       and d.get("predicted")
                       and d["observed"] >=
                       MISPREDICT_FACTOR * d["predicted"]]
                knobs = sorted({d.get("knob", "?") for d in bad}) or \
                    ["?"]
                problems.append(
                    f"{a.session_id} query {q.query_id}: cost model "
                    f"MISPREDICTED {pl['mispredicts']} decision(s) "
                    f"({', '.join(knobs)}) — observed cost >= 4x the "
                    "prediction; the evidence folds back, but repeated "
                    "mispredicts on the same site mean the workload "
                    "shifts faster than the EMA converges "
                    "(docs/performance.md \"Self-tuning planner\")")
            for cmev in q.costmodel:
                problems.append(
                    f"{a.session_id} query {q.query_id}: cost-model "
                    "evidence degraded to built-in defaults "
                    f"({cmev.get('reason', '?')}) — decisions still "
                    "made, never a failed query; check the "
                    "costModel.dir store's health")
            if q.jitcache:
                reasons = sorted({j.get("reason", "?").split(":")[0]
                                  for j in q.jitcache})
                problems.append(
                    f"{a.session_id} query {q.query_id}: "
                    f"{len(q.jitcache)} persistent jit-cache entr"
                    f"{'y' if len(q.jitcache) == 1 else 'ies'} dropped "
                    f"({', '.join(reasons)}) — recompiled fresh (never "
                    "wrong results); check jitCache.dir storage health")
            spilled = sum(q.spill.values()) if q.spill else 0
            if spilled:
                problems.append(
                    f"{a.session_id} query {q.query_id}: spilled "
                    f"{spilled} bytes")
            retries = q.retry.get("retryCount", 0) if q.retry else 0
            splits = q.retry.get("splitAndRetryCount", 0) if q.retry else 0
            if retries or splits:
                problems.append(
                    f"{a.session_id} query {q.query_id}: device OOM "
                    f"recovered — {retries} retries, {splits} splits")
            for r in q.recovery:
                problems.append(
                    f"{a.session_id} query {q.query_id}: recovery "
                    f"action {r.get('action')} after "
                    f"{r.get('fault')} fault")
            problems.extend(_watchdog_problems(
                f"{a.session_id} query {q.query_id}", q.watchdog))
            problems.extend(_corruption_problems(
                f"{a.session_id} query {q.query_id}", q.corruption))
            problems.extend(_checkpoint_problems(
                f"{a.session_id} query {q.query_id}", q.checkpoint,
                recovered=bool(q.recovery)))
            if q.fatal:
                acts = [r.get("action") for r in
                        q.fatal.get("recovery", [])]
                problems.append(
                    f"{a.session_id} query {q.query_id}: fatal after "
                    f"ladder [{', '.join(a for a in acts if a)}] — "
                    f"{q.fatal.get('error', '?')}")
            for b in q.budget:
                problems.append(
                    f"{a.session_id} query {q.query_id}: "
                    f"{b.get('budget')} budget "
                    f"{'exhausted — rejected' if b.get('action') == 'reject' else 'pressure — self-spilled'} "
                    f"({b.get('used')} > {b.get('limit')})")
            adm = q.admission
            if adm and q.duration_ms and \
                    adm.get("waitMs", 0.0) > max(
                        5 * q.duration_ms, 1000.0):
                problems.append(
                    f"{a.session_id} query {q.query_id}: admission "
                    f"starvation — waited {adm['waitMs']:.0f}ms to run "
                    f"{q.duration_ms:.0f}ms; raise serving."
                    "concurrentQueries or spread the tenant load")
        # persistent-cache thrash: a REPEAT of the same plan (matched by
        # normalized logical plan, the compare_apps discipline) that
        # still compiled fresh with zero warm hits — the tier is
        # configured but buying nothing (wrong dir, version churn, or
        # every entry failing verification)
        import re as _re
        seen_plans: Dict[str, int] = {}
        for q in a.queries:
            fu = q.fusion
            if not fu or not fu.get("persistentEnabled"):
                continue
            key = _re.sub(r"\d+", "N", q.logical_plan.strip())
            if not key:
                continue
            if key in seen_plans and fu.get("persistentMisses", 0) > 0 \
                    and fu.get("persistentHits", 0) == 0:
                problems.append(
                    f"{a.session_id} query {q.query_id}: persistent jit "
                    "cache 0% hit on a REPEAT of query "
                    f"{seen_plans[key]} ({fu['persistentMisses']} "
                    "misses, 0 hits) — warm start bought nothing; "
                    "check jitCache.dir persistence and jax/jaxlib "
                    "version churn")
            seen_plans.setdefault(key, q.query_id)
        # result-cache thrash: the cache is ON and the SAME normalized
        # plan repeated, yet no repeat ever hit — every entry is being
        # invalidated (inputs that move every query) or the results
        # never fit maxBytes; the store is configured but buying
        # nothing
        rc_on = str(a.conf.get(
            "spark.rapids.tpu.serving.resultCache.enabled",
            "")).lower() in ("1", "true", "yes", "on")
        if rc_on:
            plan_counts: Dict[str, int] = {}
            for q in a.queries:
                key = _re.sub(r"\d+", "N", q.logical_plan.strip())
                if key:
                    plan_counts[key] = plan_counts.get(key, 0) + 1
            repeats = sum(n - 1 for n in plan_counts.values() if n > 1)
            hit_any = any(
                q.sharing.get("resultCacheHit") for q in a.queries
            ) or any(e.get("kind") == "hit" and
                     e.get("store") == "result"
                     for q in a.queries for e in q.sharing_events)
            if repeats and not hit_any:
                problems.append(
                    f"{a.session_id}: result cache 0% hit over "
                    f"{repeats} repeat(s) of the same plan shape — "
                    "the store is on but buying nothing (inputs "
                    "mutating every query, results over "
                    "resultCache.maxBytes, or uncacheable "
                    "UDF/pandas plans)")
        # template tier that bought nothing: the SAME template
        # fingerprint repeated after warmup, yet repeats still
        # re-traced (jit misses) or nothing was hoistable at all —
        # the refusal list (plan/template.py hoisting rules) says
        # which literals stayed inline and why
        by_tpl: Dict[str, list] = {}
        for q in a.queries:
            t = (q.sharing or {}).get("template")
            if t and t.get("fingerprint"):
                by_tpl.setdefault(t["fingerprint"], []).append(q)
        for fp, qs in by_tpl.items():
            if len(qs) < 2:
                continue
            refusals = sorted({r for q in qs for r in
                               (q.sharing["template"]
                                .get("refusals") or [])})
            why = (f"refused literals: {', '.join(refusals)}"
                   if refusals else "no literals in the plan")
            retraced = [q for q in qs[1:]
                        if q.pipeline.get("jitCacheMisses", 0) > 0]
            if all(q.sharing["template"].get("params", 0) == 0
                   for q in qs):
                problems.append(
                    f"{a.session_id}: template {fp} repeated "
                    f"{len(qs)}x but nothing was hoisted — template "
                    f"tier bought nothing ({why})")
            elif retraced:
                problems.append(
                    f"{a.session_id}: template {fp} re-traced on "
                    f"{len(retraced)} repeat(s) after warmup "
                    f"(query {retraced[0].query_id}) — template tier "
                    f"bought nothing ({why})")
        # interleaver starvation: a query spent far longer blocked at
        # the timeslice gate than doing its own work — co-tenant
        # quanta are too coarse for this mix
        for q in a.queries:
            il = q.sharing.get("interleave") if q.sharing else None
            # gate waits happen INSIDE the query wall (waitMs <=
            # durationMs), so starvation compares the wait to the
            # query's OWN work: duration minus the wait itself
            if il and q.duration_ms and il.get("waitMs", 0.0) > max(
                    5 * (q.duration_ms - il.get("waitMs", 0.0)),
                    1000.0):
                problems.append(
                    f"{a.session_id} query {q.query_id}: interleaver "
                    f"starvation — {il['waitMs']:.0f}ms at the "
                    f"timeslice gate of a {q.duration_ms:.0f}ms "
                    "query; lower co-tenant quanta "
                    "(serving.interleave.quantumBatches) or raise "
                    "this query's weight")
        for j in a.jitcache:
            problems.append(
                f"{a.session_id}: persistent jit-cache entry dropped "
                f"without query attribution ({j.get('reason', '?')})")
        for cmev in a.costmodel:
            problems.append(
                f"{a.session_id}: cost-model evidence degraded to "
                f"built-in defaults ({cmev.get('reason', '?')}) — "
                "decisions still made, never a failed query")
        for r in a.rejections:
            problems.append(
                f"{a.session_id}: query rejected at admission "
                f"({r.get('reason')}) — the session was saturated; "
                "the rejection is the isolation working, but clients "
                "saw a typed AdmissionFault")
        for b in a.budget:
            problems.append(
                f"{a.session_id}: {b.get('budget')} budget event "
                f"without query attribution (action={b.get('action')})")
        if a.max_concurrent() > 1 and (a.recovery or a.watchdog or
                                       a.corruption):
            kinds = [k for k, evs in (("recovery", a.recovery),
                                      ("watchdog", a.watchdog),
                                      ("corruption", a.corruption))
                     if evs]
            problems.append(
                f"{a.session_id}: {'/'.join(kinds)} events without "
                "query attribution while queries ran concurrently — "
                "possible cross-query interference; every robustness "
                "event should carry the owning query's id "
                "(serving/context.py)")
        for r in a.recovery:
            problems.append(
                f"{a.session_id}: recovery action {r.get('action')} "
                f"after {r.get('fault')} fault")
        problems.extend(_watchdog_problems(a.session_id, a.watchdog))
        problems.extend(_corruption_problems(a.session_id,
                                             a.corruption))
        problems.extend(_checkpoint_problems(
            a.session_id, a.checkpoint, recovered=bool(a.recovery)))
        problems.extend(_incremental_problems(
            a.session_id,
            list(a.incremental) + [e for q in a.queries
                                   for e in q.incremental]))
        problems.extend(_fleet_problems(a))
        for f in a.fatal:
            problems.append(
                f"{a.session_id}: fatal query (no attributed id) — "
                f"{f.get('error', '?')}")
    return problems


def _checkpoint_problems(who: str, events: List[dict],
                         recovered: bool = False) -> List[str]:
    """Stage-checkpoint health: eviction thrash (the lineage budget
    cannot hold one stage, so resumes always fall back to full
    re-runs), recoveries that paid the write cost but resumed nothing
    (<1 stage saved across the whole ladder), and payloads that
    failed verification (dropped + subtree re-run — informative, the
    data was never wrong)."""
    out = []
    writes = sum(1 for c in events if c.get("kind") == "write")
    evicts = sum(1 for c in events if c.get("kind") == "evict")
    resumes = sum(1 for c in events if c.get("kind") == "resume")
    crc = [c for c in events if c.get("kind") == "invalid"
           and str(c.get("reason", "")).startswith("crc")]
    if writes and evicts >= writes:
        out.append(
            f"{who}: checkpoint eviction thrash — {evicts} evictions "
            f"over {writes} writes; recovery.checkpoint.maxBytes "
            "cannot hold one stage, so resumes degrade to full "
            "re-runs")
    if recovered and writes and not resumes:
        out.append(
            f"{who}: recovery re-drove the query but resumed <1 "
            f"stage from {writes} written checkpoint(s) — the write "
            "cost bought nothing (evicted/invalidated lineage, or "
            "the fault landed in the first stage)")
    if crc:
        out.append(
            f"{who}: {len(crc)} checkpoint payload(s) failed "
            "verification — dropped and re-run from source (never "
            "wrong bytes); check spill storage health")
    return out


def _incremental_problems(who: str, events: List[dict]) -> List[str]:
    """Continuous-ingest health: ticks that reused zero state after
    the first epoch (the standing query pays full-recompute latency —
    the whole point of incremental state bought nothing), a high
    rollback rate (faults keep killing ticks mid-flight), and
    state-eviction thrash (maxStateBytes cannot hold one epoch, so
    every tick recomputes)."""
    out = []
    commits = [e for e in events if e.get("kind") == "commit"]
    rollbacks = sum(1 for e in events if e.get("kind") == "rollback")
    evicts = sum(1 for e in events if e.get("kind") == "evict")
    cold = [e for e in commits
            if e.get("epoch", 1) > 1 and e.get("mode") == "full"
            and not e.get("reusedState")]
    if cold:
        out.append(
            f"{who}: {len(cold)} ingest tick(s) after the first epoch "
            "reused ZERO standing state (full recompute) — evicted/"
            "invalidated state or a fingerprint that moves every tick; "
            "incremental.maxStateBytes and input stability are the "
            "knobs")
    if commits and rollbacks > max(1, len(commits) // 2):
        out.append(
            f"{who}: {rollbacks} epoch rollback(s) over {len(commits)} "
            "commit(s) — mid-tick faults keep discarding provisional "
            "state; the ingest answers correctly but pays "
            "rollback + full-recompute latency every time")
    if commits and evicts >= len(commits):
        out.append(
            f"{who}: incremental state eviction thrash — {evicts} "
            f"evictions over {len(commits)} commit(s); "
            "incremental.maxStateBytes cannot hold one epoch, so "
            "every tick degrades to full recompute")
    # watermark-stalled state growth: a windowed standing query whose
    # event-time watermark stopped advancing while its state keeps
    # growing — eviction can no longer bound the state (stale event
    # times in the ingest, a delay larger than the data horizon, or a
    # stuck source clock), so "bounded under infinite ingest" is off.
    # Grouped per standing query (the event's `store` id): pooling
    # would let one ADVANCING query's watermarks mask a stalled
    # co-tenant's forever
    by_store: Dict[object, list] = {}
    for e in events:
        if e.get("kind") == "watermark" and \
                e.get("watermark") is not None:
            by_store.setdefault(e.get("store"), []).append(e)
    for store, wms in sorted(by_store.items(),
                             key=lambda kv: str(kv[0])):
        # judge the TAIL of the trail, not its whole history: a query
        # that advanced normally and then stalled (the realistic
        # pattern — source clock sticks mid-life) must still flag;
        # full-trail constancy would be masked by any early advance
        wms = wms[-5:]
        if len(wms) < 3 or len({e["watermark"] for e in wms}) != 1:
            continue
        sizes = [e.get("stateBytes", 0) for e in wms]
        if sizes[-1] > sizes[0] and \
                all(b >= a for a, b in zip(sizes, sizes[1:])):
            out.append(
                f"{who}: watermark-stalled state growth (standing "
                f"query {store}) — the event-time watermark sat at "
                f"{wms[0]['watermark']} across {len(wms)} commits "
                f"while state grew {sizes[0]} -> {sizes[-1]} bytes; "
                "eviction is not bounding this standing query (check "
                "ingest event times vs "
                "incremental.watermarkDelayMs)")
    # exactly-once violation: the same standing query committing a
    # NEW (non-replayed) sink record under one epoch twice means a
    # downstream sink saw an answer twice — the invariant the sink
    # log exists to hold.  Replays are the sanctioned path and are
    # excluded.
    sink_seen: Dict[object, set] = {}
    for e in events:
        if e.get("kind") == "sink" and not e.get("replayed"):
            seen = sink_seen.setdefault(e.get("store"), set())
            ep = e.get("epoch")
            if ep in seen:
                out.append(
                    f"{who}: duplicate sink emission (standing query "
                    f"{e.get('store')}, epoch {ep}) — a downstream "
                    "sink saw one committed answer twice; the "
                    "exactly-once contract is broken")
            seen.add(ep)
    # fleet fan-out that stopped sharing: every round pulling the
    # source once PER SUBSCRIBER means the shared-ingest loan is
    # never usable (schema drift, metadata columns, subscriber
    # backlogs) and the fleet pays lone-runner cost
    rounds = [e for e in events if e.get("kind") == "round"
              and e.get("subscribers", 0) > 1
              and e.get("deltaFiles", 0) > 0]
    if rounds:
        unshared = [e for e in rounds
                    if e.get("sourcePulls", 0) >
                    e.get("deltaFiles", 0)]
        if len(unshared) == len(rounds):
            out.append(
                f"{who}: every fleet round ({len(rounds)}) pulled the "
                "source once per subscriber — the shared-ingest loan "
                "was never usable (mismatched fact scans, metadata "
                "columns, or subscriber catch-up backlogs); the fleet "
                "is paying N-lone-runner ingest cost")
    return out


def _watchdog_problems(who: str, events: List[dict]) -> List[str]:
    """Hang-detection lines: per-point trips with deadline margin, and
    delivered cancellations."""
    out = []
    for w in events:
        point = w.get("point", "?")
        if w.get("kind") == "trip":
            out.append(
                f"{who}: hang detected at {point} — exceeded its "
                f"{w.get('deadlineMs', 0):.0f}ms deadline by "
                f"{w.get('overrunMs', 0):.0f}ms")
        else:
            out.append(
                f"{who}: watchdog cancellation delivered for {point} "
                f"({w.get('elapsedMs', 0):.0f}ms elapsed) — query "
                "re-driven by the recovery ladder")
    return out


def _corruption_problems(who: str, events: List[dict]) -> List[str]:
    out = []
    if events:
        tiers = sorted({c.get("tier", "?") for c in events})
        out.append(
            f"{who}: {len(events)} spill payload(s) failed checksum "
            f"verification (tier {', '.join(tiers)}) — batches "
            "dropped and re-run from source; check spill storage "
            "health")
    return out


def plan_dot(q: QueryInfo) -> str:
    """Physical plan as a DOT digraph (GenerateDot.scala analog)."""
    lines = q.physical_plan.splitlines()
    out = ["digraph plan {", "  rankdir=BT;",
           '  node [shape=box, fontname="monospace"];']
    # indentation encodes the tree
    stack: List[Tuple[int, int]] = []  # (depth, node_id)
    for i, raw in enumerate(lines):
        depth = (len(raw) - len(raw.lstrip())) // 2
        label = raw.strip().replace('"', r'\"')
        out.append(f'  n{i} [label="{label}"];')
        while stack and stack[-1][0] >= depth:
            stack.pop()
        if stack:
            out.append(f"  n{i} -> n{stack[-1][1]};")
        stack.append((depth, i))
    out.append("}")
    return "\n".join(out)


# phase stripe palette for span-traced query bars (fixed order so
# every bar reads the same left-to-right: compile, exchange, compute,
# spill, wait, then the unattributed remainder in grey)
_PHASE_COLORS = (("compile", "#e9c46a"), ("exchange", "#2a9d8f"),
                 ("compute", "#4c956c"), ("spill", "#d1495b"),
                 ("wait", "#b8b8ff"))
_UNATTRIBUTED_COLOR = "#cccccc"


def _phase_stripes(q: QueryInfo, x0: float, y: int, w: float,
                   h: int) -> List[str]:
    """Per-query phase stripes from the span rollup: each phase's
    exclusive time becomes a proportional segment of the query bar.
    Returns [] when the query has no span rollup (pre-span logs fall
    back to the solid status bar)."""
    sp = q.spans
    phases = (sp or {}).get("phases") or {}
    wall = (sp or {}).get("wallMs", 0.0)
    if not phases or wall <= 0 or not q.succeeded:
        return []  # failed/pre-span queries keep the solid status bar
    out = []
    x = x0
    segs = [(name, phases.get(name, 0.0)) for name, _ in _PHASE_COLORS
            if phases.get(name, 0.0) > 0]
    covered = sum(ms for _, ms in segs)
    segs.append(("unattributed", max(wall - covered, 0.0)))
    # worker-thread spans overlap the driver's wall, so summed phase
    # time can exceed it: normalize by the larger of the two so the
    # stripes always fill exactly the query's bar
    total = max(covered, wall)
    colors = dict(_PHASE_COLORS)
    colors["unattributed"] = _UNATTRIBUTED_COLOR
    for name, ms in segs:
        seg_w = w * min(ms / total, 1.0)
        if seg_w < 0.1:
            continue
        out.append(
            f"<rect x='{x:.1f}' y='{y + 4}' width='{seg_w:.1f}' "
            f"height='{h - 10}' fill='{colors[name]}'>"
            f"<title>q{q.query_id} {name}: {ms:.1f} ms</title></rect>")
        x += seg_w
    return out


def generate_timeline(apps: List[AppInfo]) -> str:
    """SVG timeline: one lane per session, one bar per query, colored by
    status (the GenerateTimeline.scala:494 role — theirs draws tasks per
    executor; a single-controller SPMD engine's unit of work is the
    query).  Queries carrying a span rollup (QueryInfo.spans) render as
    phase stripes — compile / exchange / compute / spill / wait — with
    the unattributed remainder in grey; pre-span logs keep the old
    solid bars."""
    apps = [a for a in apps if a.queries]
    if not apps:
        return "<svg xmlns='http://www.w3.org/2000/svg'/>"
    t0 = min(min((q.start_ts or a.start_ts) for q in a.queries)
             for a in apps)
    t1 = max(a.end_ts for a in apps)
    span = max(t1 - t0, 1e-3)
    width, lane_h, pad, label_w = 900, 26, 6, 180
    h = pad * 2 + lane_h * len(apps) + 30
    scale = (width - label_w - pad * 2) / span

    def x(ts):
        return label_w + pad + (ts - t0) * scale

    colors = {"success": "#4c956c", "incomplete": "#b8b8ff"}
    out = [f"<svg xmlns='http://www.w3.org/2000/svg' width='{width}' "
           f"height='{h}' font-family='monospace' font-size='11'>"]
    for i, a in enumerate(apps):
        y = pad + i * lane_h
        out.append(f"<text x='{pad}' y='{y + lane_h - 10}'>"
                   f"{a.session_id[:24]}</text>")
        out.append(f"<line x1='{label_w}' y1='{y + lane_h - 4}' "
                   f"x2='{width - pad}' y2='{y + lane_h - 4}' "
                   f"stroke='#ddd'/>")
        for q in a.queries:
            qs = q.start_ts or a.start_ts
            qe = q.end_ts or (qs + q.duration_ms / 1e3)
            w = max((qe - qs) * scale, 2.0)
            stripes = _phase_stripes(q, x(qs), y, w, lane_h)
            if stripes:
                out.extend(stripes)
                continue
            color = colors.get(q.status, "#d1495b")
            out.append(
                f"<rect x='{x(qs):.1f}' y='{y + 4}' width='{w:.1f}' "
                f"height='{lane_h - 10}' fill='{color}'>"
                f"<title>q{q.query_id}: {q.duration_ms:.1f} ms "
                f"[{q.status}]</title></rect>")
    axis_y = pad + len(apps) * lane_h + 14
    out.append(f"<text x='{label_w}' y='{axis_y}'>0s</text>")
    out.append(f"<text x='{width - 60}' y='{axis_y}'>{span:.1f}s</text>")
    out.append("</svg>")
    return "\n".join(out)


def compare_apps(apps: List[AppInfo]) -> str:
    """Side-by-side session comparison (CompareApplications.scala role):
    per-app totals, then per-query durations matched across apps by
    query id, flagging big regressions."""
    out = ["-- Application comparison --",
           f"{'session':28s} {'queries':>8s} {'total_ms':>10s} "
           f"{'spill_B':>10s} {'fallbacks':>9s}"]
    for a in apps:
        spilled = sum(sum(q.spill.values()) for q in a.queries if q.spill)
        fb = sum(len(q.fallback_ops()) for q in a.queries)
        out.append(f"{a.session_id[:28]:28s} {len(a.queries):8d} "
                   f"{a.total_duration_ms:10.1f} {spilled:10d} {fb:9d}")
    # query ids are engine-global counters, so cross-session identity is
    # the LOGICAL PLAN text (the role SQL ids play in
    # CompareApplications.scala)
    def plans(a):
        import re
        seen = {}
        for q in a.queries:
            # normalize data-dependent literals (row counts in relation
            # describe strings) so the same query over different data
            # sizes still matches
            key = re.sub(r"\d+", "N", q.logical_plan.strip())
            if key and key not in seen:
                seen[key] = q
        return seen
    per_app = [plans(a) for a in apps]
    keys = sorted(set.intersection(*[set(p) for p in per_app])) \
        if len(apps) >= 2 else []
    if keys:
        out.append("\n-- Matched queries (by logical plan) --")
        header = f"{'plan':34s}" + "".join(
            f" {a.session_id[:14]:>16s}" for a in apps)
        out.append(header + f" {'max/min':>8s}")
        for key in keys:
            durs = [p[key].duration_ms for p in per_app]
            ratio = (max(durs) / min(durs)) if min(durs) else 0.0
            flag = "  <-- regression" if ratio >= 2.0 else ""
            label = key.splitlines()[0][:34]
            out.append(f"{label:34s}" + "".join(
                f" {d:16.1f}" for d in durs) + f" {ratio:8.2f}{flag}")
    return "\n".join(out)


def format_report(apps: List[AppInfo], top: int) -> str:
    out = ["=" * 72, "TPU Profiling Report", "=" * 72]
    out.append(f"\nSessions: {len(apps)}, queries: "
               f"{sum(len(a.queries) for a in apps)}")
    out.append("\n-- Operator aggregate (by total opTime) --")
    out.append(f"{'operator':40s} {'time_ms':>10s} {'rows':>12s} "
               f"{'uses':>6s}")
    for name, ms, rows, n in aggregate_ops(apps)[:top]:
        out.append(f"{name:40s} {ms:10.2f} {rows:12d} {n:6d}")
    out.append("\n-- Slowest queries --")
    for sid, q in slowest_queries(apps, top):
        out.append(f"  {sid} q{q.query_id}: {q.duration_ms:.1f} ms "
                   f"[{q.status}]")
    sk = skew_stats(apps)
    if sk:
        out.append("\n-- Duration distribution --")
        out.append(f"  n={sk['queries']} mean={sk['mean_ms']:.1f}ms "
                   f"p50={sk['p50_ms']:.1f}ms max={sk['max_ms']:.1f}ms "
                   f"skew={sk['skew_ratio']:.2f}x")
    pl = pipeline_stats(apps)
    if pl:
        out.append("\n-- Async pipeline --")
        out.append(
            f"  pipelined queries={pl['queries']} "
            f"batches={pl['batches']} "
            f"fill={pl['fill_ratio']:.2f} "
            f"hostSyncs={pl['host_sync_count']} "
            f"uploadOverlap={pl['upload_overlap_ms']:.1f}ms")
        total = pl["jit_cache_hits"] + pl["jit_cache_misses"]
        if total:
            out.append(
                f"  jit cache: {pl['jit_cache_hits']}/{total} hits "
                f"({pl['jit_cache_hits'] / total:.0%})")
    sw = shuffle_wire_stats(apps)
    if sw:
        out.append("\n-- Shuffle wire --")
        out.append(
            f"  distributed queries={sw['queries']} "
            f"exchanges={sw['exchanges']} "
            f"collectives={sw['collectives']} "
            f"bytes={sw['bytes_moved']} "
            f"padding={sw['padding_ratio']:.2f}x "
            f"overflowRetries={sw['slot_overflow_retries']} "
            f"perColumnFallbacks={sw['per_column_fallbacks']}")
        if sw.get("async_exchanges") or sw.get("host_staged_bytes") \
                or sw.get("ragged_exchanges"):
            out.append(
                f"  exchange overlap={sw['exchange_overlap_ms']:.1f}ms"
                f"/{sw['exchange_wall_ms']:.1f}ms "
                f"({sw['overlap_fraction']:.0%}) "
                f"async={sw['async_exchanges']} "
                f"ragged={sw['ragged_exchanges']} "
                f"hostStaged={sw['host_staged_bytes']}B")
        if sw.get("encoded_bytes_saved") or \
                sw.get("encodable_decoded_exchanges"):
            total = sw["bytes_moved"] + sw["encoded_bytes_saved"]
            out.append(
                f"  encoded wire: saved={sw['encoded_bytes_saved']}B "
                f"({sw['encoded_bytes_saved'] / max(total, 1):.0%} of "
                f"decoded) dictDelta={sw['wire_dict_bytes']}B "
                f"dictFallbacks={sw['wire_dict_fallbacks']} "
                f"shippedDecoded={sw['encodable_decoded_exchanges']}")
    fu = fusion_stats(apps)
    if fu:
        out.append("\n-- Whole-stage fusion & compile cache --")
        out.append(
            f"  fusedStages={fu['fused_stages']} "
            f"fusedOperators={fu['fused_operators']} "
            f"dispatchesSaved={fu['dispatches_saved']} "
            f"fusibleChains={fu['fusible_chains']} "
            f"encodedStages={fu['encoded_stages']}")
        ptotal = fu["persistent_hits"] + fu["persistent_misses"]
        if ptotal or fu["persistent_stores"]:
            out.append(
                f"  persistent jit cache: {fu['persistent_hits']}/"
                f"{ptotal} warm hits, stores={fu['persistent_stores']} "
                f"invalid={fu['persistent_invalid']}")
    ss = span_stats(apps)
    if ss:
        out.append("\n-- Where the time went (span tracing) --")
        out.append(
            f"  traced queries={ss['queries']} "
            f"wall={ss['wall_ms']:.1f}ms "
            f"attributed={ss['exclusive_ms']:.1f}ms "
            f"unattributed={ss['unattributed_ms']:.1f}ms "
            f"({ss['unattributed_frac']:.0%}) "
            f"asyncOverlap={ss['overlap_ms']:.1f}ms")
        if ss["phases"]:
            out.append("  phases: " + "  ".join(
                f"{k}={v:.1f}ms" for k, v in ss["phases"].items()))
        for pt, ms in ss["top_points"][:8]:
            out.append(f"    {pt:36s} {ms:10.2f} ms")
    cc = concurrency_stats(apps)
    if cc:
        out.append("\n-- Concurrency & admission --")
        out.append(
            f"  maxConcurrent={cc['max_concurrent']} "
            f"admitted={cc['admitted']} rejected={cc['rejected']} "
            f"waitTotal={cc['total_wait_ms']:.1f}ms "
            f"p50={cc['p50_wait_ms']:.1f}ms "
            f"p95={cc['p95_wait_ms']:.1f}ms "
            f"budgetEvents={cc['budget_events']}")
    cp = checkpoint_stats(apps)
    if cp:
        out.append("\n-- Stage checkpoints --")
        out.append(
            f"  writes={cp['writes']} "
            f"bytes={cp['bytes_written']} "
            f"resumes={cp['resumes']} "
            f"stagesSkipped={cp['stages_skipped']} "
            f"evictions={cp['evictions']} "
            f"invalidations={cp['invalidations']}")
    sh = sharing_stats(apps)
    if sh:
        out.append("\n-- Cross-query reuse --")
        out.append(
            f"  resultCache: hits={sh['result_cache_hits']} "
            f"misses={sh['result_cache_misses']} "
            f"stores={sh['result_cache_stores']} "
            f"invalidations={sh['invalidations']} "
            f"evictions={sh['evictions']}")
        if sh["template_cache_hits"] or sh["template_cache_misses"] \
                or sh["template_cache_stores"]:
            out.append(
                f"  templateCache: hits={sh['template_cache_hits']} "
                f"misses={sh['template_cache_misses']} "
                f"stores={sh['template_cache_stores']}")
        out.append(
            f"  sharedStages: writes={sh['stage_writes']} "
            f"splices={sh['stage_splices']}")
        if sh["interleaved_queries"]:
            out.append(
                f"  interleaver: queries={sh['interleaved_queries']} "
                f"timeslices={sh['timeslices']:.0f} "
                f"wait={sh['interleave_wait_ms']:.1f}ms")
    pdec = planner_stats(apps)
    if pdec:
        out.append("\n-- Planner decisions (cost model) --")
        out.append(
            f"  queries={pdec['queries']} "
            f"decisions={pdec['decisions']} "
            f"evidence={pdec['evidence_decisions']} "
            f"overrides={pdec['override_decisions']} "
            f"replans={pdec['replans']} "
            f"mispredicts={pdec['mispredicts']} "
            f"degradedLoads={pdec['invalid_loads']}")
        if pdec["by_knob"]:
            out.append("  knobs: " + "  ".join(
                f"{k}={v}" for k, v in pdec["by_knob"].items()))
        if pdec["exchange_modes"]:
            out.append("  exchange modes: " + "  ".join(
                f"{k}={v}" for k, v in pdec["exchange_modes"].items()))
    ic = incremental_stats(apps)
    if ic:
        out.append("\n-- Continuous ingest --")
        out.append(
            f"  epochs={ic['commits']} "
            f"incremental={ic['incremental_ticks']} "
            f"fullRecomputes={ic['full_recomputes']} "
            f"reuse={ic['reuse_ratio']:.2f} "
            f"rollbacks={ic['rollbacks']} "
            f"stateEvictions={ic['state_evictions']} "
            f"spliceResumes={ic['splice_resumes']} "
            f"stateBytes={ic['state_bytes']}")
        if ic.get("watermark") is not None:
            out.append(
                f"  watermark={ic['watermark']} "
                f"evictedBuckets={ic['watermark_evicted_buckets']} "
                f"evictedBytes={ic['watermark_evicted_bytes']}")
        if ic.get("sink_commits") or ic.get("sink_replays"):
            out.append(
                f"  sinks: commits={ic['sink_commits']} "
                f"replays={ic['sink_replays']}")
        if ic.get("fleet_rounds"):
            out.append(
                f"  fleet: rounds={ic['fleet_rounds']} "
                f"sourcePulls={ic['fleet_source_pulls']} "
                f"splices={ic['fleet_splices']} "
                f"failures={ic['fleet_failures']}")
    fl = fleet_stats(apps)
    if fl:
        out.append("\n-- Fleet membership --")
        out.append(
            f"  hosts={fl['hosts_seen']} joins={fl['joins']} "
            f"losses={fl['losses']} "
            f"meshShrinks={fl['mesh_shrinks']} "
            f"fenceBumps={fl['fence_bumps']} "
            f"fencedPublishes={fl['fenced_publishes']} "
            f"fleetCrossHits={fl['fleet_cross_hits']}")
        if fl.get("suspects") or fl.get("hedges_fired") \
                or fl.get("quarantines") or fl.get("rejoins"):
            out.append("\n-- Fleet health --")
            out.append(
                f"  suspects={fl['suspects']} "
                f"recoveries={fl['suspect_recoveries']} "
                f"quarantines={fl['quarantines']} "
                f"rejoins={fl['rejoins']} "
                f"hedgesFired={fl['hedges_fired']} "
                f"hedgesWon={fl['hedges_won']} "
                f"duplicatesSuppressed={fl['duplicates_suppressed']}")
            # per-host score timeline: each state transition with the
            # score that drove it, in log order — the gray-failure
            # post-mortem trail (when did it go bad, how bad, when did
            # it come back)
            for a in apps:
                line = []
                for ev in a.fleet:
                    k = ev.get("kind")
                    if k in ("suspect", "recovered", "quarantine",
                             "rejoin"):
                        sc = ev.get("score")
                        tag = f"{k}@host{ev.get('host')}"
                        if sc is not None:
                            tag += f"(x{sc})"
                        line.append(tag)
                if line:
                    out.append(
                        f"  {a.session_id}: " + " -> ".join(line))
    problems = health_check(apps)
    out.append("\n-- Health check --")
    if problems:
        out.extend(f"  ! {p}" for p in problems)
    else:
        out.append("  no failures, no spill")
    return "\n".join(out)


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="spark_rapids_tpu.tools.profiling", description=__doc__)
    ap.add_argument("logdir")
    ap.add_argument("--top", type=int, default=10)
    ap.add_argument("--dot", type=int, default=None, metavar="QUERYID",
                    help="print a DOT graph of this query's physical plan")
    ap.add_argument("--timeline", metavar="FILE.svg", default=None,
                    help="write an SVG timeline of all sessions/queries")
    ap.add_argument("--compare", action="store_true",
                    help="side-by-side comparison of the loaded sessions")
    ap.add_argument("--filter-app", metavar="REGEX", default=None,
                    help="only sessions whose id matches the regex")
    ap.add_argument("--started-after", type=float, default=None,
                    metavar="EPOCH", help="only sessions started at/after "
                    "this epoch-seconds timestamp")
    ap.add_argument("--newest", type=int, default=None, metavar="N",
                    help="only the N most recently started sessions")
    ap.add_argument("--site-history", metavar="OBS_DIR", default=None,
                    help="also print the per-site observation history "
                    "persisted beside the AOT cache dir "
                    "(utils/tracing.ObservationStore)")
    args = ap.parse_args(argv)
    if args.site_history and args.logdir == "-":
        # site history needs no event log: allow '-' as the logdir
        print(site_history(args.site_history))
        return 0
    from spark_rapids_tpu.tools.eventlog import filter_apps
    apps = filter_apps(load_logs(args.logdir), match=args.filter_app,
                       started_after=args.started_after,
                       newest=args.newest)
    if not apps:
        print("no event logs found", file=sys.stderr)
        return 1
    if args.timeline:
        with open(args.timeline, "w", encoding="utf-8") as fh:
            fh.write(generate_timeline(apps))
        print(f"wrote {args.timeline}")
        return 0
    if args.compare:
        print(compare_apps(apps))
        return 0
    if args.dot is not None:
        for a in apps:
            for q in a.queries:
                if q.query_id == args.dot:
                    print(plan_dot(q))
                    return 0
        print(f"query {args.dot} not found", file=sys.stderr)
        return 1
    print(format_report(apps, args.top))
    if args.site_history:
        print()
        print(site_history(args.site_history))
    return 0


if __name__ == "__main__":
    sys.exit(main())
