"""API-surface validation tool.

Counterpart of the reference's ``api_validation`` module
(``ApiValidation.scala``): there it reflects over GPU exec constructor
signatures and diffs them against Spark's to catch silent API drift
between releases.  This engine has no host Spark to diff against, so the
audit runs against a RECORDED golden manifest (``api_manifest.json`` at
the repo root): the public API surface — DataFrame/Column/functions/
Session methods, registered expression rules, logical plan nodes,
physical execs, and config keys — is collected by introspection and
compared entry-by-entry.

* an entry in the manifest but missing from the code = REMOVED API
  (breaks users; the check fails)
* an entry in the code but not the manifest = new surface (reported;
  refresh the manifest with --update to accept it)

CLI:  spark-rapids-tpu-api-validation [--manifest PATH] [--update]
"""

from __future__ import annotations

import inspect
import json
import os
import sys
from typing import Dict, List

# ships inside the package so the installed console script finds it
DEFAULT_MANIFEST = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "api_manifest.json")


def _public_methods(cls) -> List[str]:
    out = []
    for name, member in inspect.getmembers(cls):
        if name.startswith("_"):
            continue
        if callable(member) or isinstance(
                inspect.getattr_static(cls, name, None), property):
            out.append(name)
    return sorted(out)


def _public_functions(module) -> List[str]:
    return sorted(
        name for name, member in inspect.getmembers(module)
        if not name.startswith("_")
        and (inspect.isfunction(member) or inspect.isclass(member))
        and getattr(member, "__module__", "").startswith(
            "spark_rapids_tpu"))


def collect_surface() -> Dict[str, List[str]]:
    """Introspect the live package for every audited surface group."""
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.dataframe import DataFrame, GroupedData
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.config import rapids_conf as rc
    from spark_rapids_tpu.plan import logical as L
    # registers its DictLookup expression rule at import time; import
    # it here so the audited surface does not depend on whether a
    # distributed query ran first in this process
    from spark_rapids_tpu.parallel import dist_planner  # noqa: F401
    from spark_rapids_tpu.plan.overrides import (
        _EXPR_RULES, _PLAN_CONVERTERS)

    from spark_rapids_tpu.exec import (  # noqa: F401 - registration
        aggregate, basic, cache, fallback, generate, join, sort, window)
    import spark_rapids_tpu.exec as exec_pkg
    execs = set()
    for mod_name in ("aggregate", "basic", "cache", "fallback",
                     "generate", "join", "sort", "window"):
        mod = getattr(exec_pkg, mod_name)
        for name, member in inspect.getmembers(mod, inspect.isclass):
            if name.startswith("Tpu") and name.endswith("Exec"):
                execs.add(name)
    from spark_rapids_tpu.udf import python_exec
    for name, _ in inspect.getmembers(python_exec, inspect.isclass):
        if name.startswith("Tpu") and name.endswith("Exec"):
            execs.add(name)

    return {
        "dataframe_methods": _public_methods(DataFrame),
        "grouped_data_methods": _public_methods(GroupedData),
        "column_methods": _public_methods(F.Col),
        "functions": _public_functions(F),
        "session_methods": _public_methods(TpuSession),
        "expression_rules": sorted(c.__name__ for c in _EXPR_RULES),
        "logical_nodes": sorted(
            n for n, m in inspect.getmembers(L, inspect.isclass)
            if issubclass(m, L.LogicalPlan) and m is not L.LogicalPlan),
        "plan_converters": sorted(c.__name__ for c in _PLAN_CONVERTERS),
        "physical_execs": sorted(execs),
        "config_keys": sorted(rc._REGISTRY),
    }


def validate(manifest_path: str = DEFAULT_MANIFEST) -> Dict[str, dict]:
    """Diff the live surface against the manifest.  Returns per-group
    {"removed": [...], "added": [...]}; any non-empty "removed" is a
    failure."""
    with open(manifest_path) as f:
        want = json.load(f)
    got = collect_surface()
    report = {}
    for group in sorted(set(want) | set(got)):
        w = set(want.get(group, []))
        g = set(got.get(group, []))
        removed = sorted(w - g)
        added = sorted(g - w)
        if removed or added:
            report[group] = {"removed": removed, "added": added}
    return report


def write_manifest(manifest_path: str = DEFAULT_MANIFEST) -> None:
    with open(manifest_path, "w") as f:
        json.dump(collect_surface(), f, indent=1, sort_keys=True)
        f.write("\n")


def main(argv: List[str] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="Audit the public API surface against the recorded "
                    "manifest (api_validation analog)")
    ap.add_argument("--manifest", default=DEFAULT_MANIFEST)
    ap.add_argument("--update", action="store_true",
                    help="rewrite the manifest from the live surface")
    args = ap.parse_args(argv)
    if args.update:
        write_manifest(args.manifest)
        print(f"manifest written: {args.manifest}")
        return 0
    if not os.path.exists(args.manifest):
        print(f"no manifest at {args.manifest}; run with --update first",
              file=sys.stderr)
        return 2
    report = validate(args.manifest)
    failed = False
    for group, diff in report.items():
        for name in diff["removed"]:
            failed = True
            print(f"REMOVED  {group}: {name}")
        for name in diff["added"]:
            print(f"added    {group}: {name}")
    if failed:
        print("\nAPI validation FAILED: entries above were removed from "
              "the public surface; restore them or update the manifest "
              "deliberately (--update).", file=sys.stderr)
        return 1
    print("API surface OK"
          + (" (new additions listed above — refresh with --update)"
             if report else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
