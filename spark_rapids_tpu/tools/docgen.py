"""Self-documenting doc generation: configs.md + supported_ops.md.

The reference generates its docs from code (RapidsConf.main ->
docs/configs.md; TypeChecks doc-gen mains -> docs/supported_ops.md); this
module does the same from the conf registry, the expression rule registry,
and the plan converter table, so the docs can never drift from the code.

Usage:  python -m spark_rapids_tpu.tools.docgen [DOCS_DIR]
"""

from __future__ import annotations

import os
import sys
from typing import List


def generate_configs_md() -> str:
    from spark_rapids_tpu.config.rapids_conf import RapidsConf
    return RapidsConf.generate_docs()


def generate_supported_ops_md() -> str:
    from spark_rapids_tpu.plan import overrides as ov
    from spark_rapids_tpu.plan import logical as L

    lines: List[str] = [
        "# Supported operators and expressions", "",
        "Generated from the planner registries "
        "(`python -m spark_rapids_tpu.tools.docgen`). An expression or "
        "operator outside this list (or used with an unsupported type) "
        "is tagged \"will not work on TPU\" and runs on the CPU "
        "fallback path.", "",
        "## Physical operators", "",
        "Logical node | TPU conversion", "---|---"]
    for cls in sorted(ov._PLAN_CONVERTERS, key=lambda c: c.__name__):
        fn = ov._PLAN_CONVERTERS[cls]
        doc = (fn.__doc__ or "").strip().splitlines()
        note = doc[0] if doc else ""
        lines.append(f"{cls.__name__} | supported{': ' + note if note else ''}")
    unconverted = [c.__name__ for c in vars(L).values()
                   if isinstance(c, type) and
                   issubclass(c, L.LogicalPlan) and c is not L.LogicalPlan
                   and c not in ov._PLAN_CONVERTERS]
    if unconverted:
        lines += ["", "CPU-only logical nodes: " +
                  ", ".join(sorted(unconverted))]

    lines += ["", "## Expressions", "",
              "Expression | Supported types | Notes", "---|---|---"]
    for cls in sorted(ov._EXPR_RULES, key=lambda c: c.__name__):
        rule = ov._EXPR_RULES[cls]
        names = sorted(rule.sig.names) + \
            (["decimal64"] if rule.sig.decimal else [])
        lines.append(f"{cls.__name__} | {', '.join(names)} | "
                     f"{rule.note}")
    return "\n".join(lines) + "\n"


def main(argv: List[str] = None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    docs_dir = args[0] if args else os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "docs")
    os.makedirs(docs_dir, exist_ok=True)
    cfg = os.path.join(docs_dir, "configs.md")
    ops = os.path.join(docs_dir, "supported_ops.md")
    with open(cfg, "w", encoding="utf-8") as f:
        f.write(generate_configs_md())
    with open(ops, "w", encoding="utf-8") as f:
        f.write(generate_supported_ops_md())
    print(f"wrote {cfg}\nwrote {ops}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
