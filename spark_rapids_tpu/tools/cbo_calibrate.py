"""CBO weight calibration: measure per-row operator costs on THIS
machine and write ``plan/cbo_weights.json``.

Round 2/3 verdicts flagged the optimizer's hardcoded ``/6.0`` "measured
speedup" as fiction (CostBasedOptimizer.scala:290-340 derives its model
from benchmarks).  This tool replaces it with numbers: each operator
kind runs a micro-benchmark through the REAL engine path (device
columnar execution, whatever device the session lands on) and through
its pandas equivalent (the CPU-fallback platform the optimizer would
revert to), recording microseconds per row for both sides.

Usage: ``spark-rapids-tpu-cbo-calibrate [out.json] [--rows N]``

``--from-observations DIR`` refreshes the weights from a site-history
directory instead of running the micro-benchmarks: the cost model's
``op:<Name>`` evidence records (observed device us/row, folded from
real queries' per-node metrics at QueryEnd) become the ``tpu`` weights,
while ``cpu`` weights carry over from the existing calibration file
(or the built-in ratio table).  Real-workload evidence beats a
micro-benchmark: the observed rates include the batch sizes, fusion
and encoding the production plans actually run with.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Dict

import numpy as np

DEFAULT_OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "plan", "cbo_weights.json")


def _time(fn, reps: int = 3) -> float:
    fn()  # warmup / compile
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def calibrate(n: int = 1 << 20) -> Dict[str, Dict[str, float]]:
    import pandas as pd

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.functions import Window
    from spark_rapids_tpu.api.session import TpuSession

    session = TpuSession()
    rng = np.random.default_rng(0)
    pdf = pd.DataFrame({
        "k": rng.integers(0, 100, n).astype(np.int64),
        "o": rng.permutation(n),
        "x": rng.uniform(-100, 100, n),
        "y": rng.uniform(0, 1, n),
    })
    df = session.create_dataframe(pdf)
    dim = pd.DataFrame({"k": np.arange(100, dtype=np.int64),
                        "w": np.arange(100) * 1.5})
    ddf = session.create_dataframe(dim)
    small = pdf.head(n // 8).assign(
        a=[list(range(i % 4)) for i in range(n // 8)])
    adf = session.create_dataframe(small)

    cases = {
        "Project": (
            lambda: df.select((F.col("x") * 2 + F.col("y")).alias("z"))
            .to_device_batches(),
            lambda: pdf.x * 2 + pdf.y),
        "Filter": (
            lambda: df.filter(F.col("x") > 0).to_device_batches(),
            lambda: pdf[pdf.x > 0]),
        "Aggregate": (
            lambda: df.groupBy("k").agg(F.sum("x").alias("s"),
                                        F.count("y").alias("c"))
            .to_device_batches(),
            lambda: pdf.groupby("k").agg(s=("x", "sum"),
                                         c=("y", "count"))),
        "Join": (
            lambda: df.join(ddf, "k").to_device_batches(),
            lambda: pdf.merge(dim, on="k")),
        "Sort": (
            lambda: df.orderBy("x").to_device_batches(),
            lambda: pdf.sort_values("x")),
        "Window": (
            lambda: df.select(F.sum("x").over(
                Window.partitionBy("k").orderBy("o")).alias("r"))
            .to_device_batches(),
            lambda: pdf.sort_values(["k", "o"]).groupby("k").x.cumsum()),
        "Generate": (
            lambda: adf.select(F.explode(F.col("a")).alias("e"))
            .to_device_batches(),
            lambda: small.explode("a")),
    }

    import jax
    out: Dict[str, Dict[str, float]] = {}
    for name, (engine, cpu) in cases.items():
        rows = n if name != "Generate" else len(small)

        def run_engine(e=engine):
            for b in e():
                for c in b.columns.values():
                    jax.block_until_ready(c.data)

        t_dev = _time(run_engine)
        t_cpu = _time(cpu)
        out[name] = {
            "tpu": round(t_dev / rows * 1e6, 6),   # us/row
            "cpu": round(t_cpu / rows * 1e6, 6),
        }
        print(f"{name:10s} device {out[name]['tpu']:9.4f} us/row   "
              f"cpu {out[name]['cpu']:9.4f} us/row", file=sys.stderr)
    return {
        "provenance": {
            "platform": jax.devices()[0].platform,
            "rows": n,
        },
        "weights": out,
    }


def from_observations(obs_dir: str) -> Dict:
    """Weights blob from a site-history directory's ``op:<Name>``
    evidence records (see module docstring)."""
    from spark_rapids_tpu.utils.tracing import ObservationStore
    records = ObservationStore.read(obs_dir)
    # ns/row in the store (us/row would round to 0.0 for fast ops);
    # zero/absent weights never become calibration entries
    observed = {sid[3:]: rec for sid, rec in records.items()
                if sid.startswith("op:")
                and float(rec.get("tpu_ns_per_row") or 0.0) > 0}
    if not observed:
        raise SystemExit(
            f"no op:<Name> observation records under {obs_dir!r}; run "
            "queries with spark.rapids.tpu.costModel.enabled (and an "
            "event log) first")
    # cpu weights carry over from the existing calibration (or the
    # built-in ratio table scaled into the same us/row domain)
    from spark_rapids_tpu.plan import cbo
    _, cpu_w = cbo.load_weights()
    out = {}
    for name, rec in observed.items():
        out[name] = {
            "tpu": round(float(rec["tpu_ns_per_row"]) / 1e3, 6),
            "cpu": round(float(cpu_w.get(name, cpu_w["default"])), 6),
        }
        print(f"{name:10s} device {out[name]['tpu']:9.4f} us/row "
              f"(observed, n={int(rec.get('n', 0))})   "
              f"cpu {out[name]['cpu']:9.4f} us/row (carried)",
              file=sys.stderr)
    import jax
    return {
        "provenance": {
            "platform": jax.devices()[0].platform,
            "source": "observations",
            "obs_dir": os.path.abspath(obs_dir),
        },
        "weights": out,
    }


def main(argv=None) -> int:
    args = list(argv if argv is not None else sys.argv[1:])
    rows = 1 << 20
    obs_dir = None
    if "--from-observations" in args:
        i = args.index("--from-observations")
        obs_dir = args[i + 1]
        del args[i:i + 2]
    if "--rows" in args:
        i = args.index("--rows")
        rows = int(args[i + 1])
        del args[i:i + 2]
    out_path = args[0] if args else DEFAULT_OUT
    result = from_observations(obs_dir) if obs_dir else calibrate(rows)
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(result, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {out_path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
