"""Shared event-log parsing for the qualification and profiling tools.

Reads the JSON-lines files the engine writes (utils/events.py), grouping
records into per-session ``AppInfo`` objects with per-query details —
the role ``ApplicationInfo``/``EventsProcessor`` play in the reference's
tools module (tools/src/main/.../profiling/ApplicationInfo.scala).
"""

from __future__ import annotations

import glob
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class QueryInfo:
    query_id: int
    logical_plan: str = ""
    physical_plan: str = ""
    explain: str = ""
    status: str = ""
    duration_ms: float = 0.0
    start_ts: float = 0.0   # epoch seconds (QueryStart record ts)
    end_ts: float = 0.0
    metrics: Dict[str, Dict[str, int]] = field(default_factory=dict)
    spill: Dict[str, int] = field(default_factory=dict)
    retry: Dict[str, int] = field(default_factory=dict)
    # async pipeline stats (exec/pipeline.py PipelineStats.as_dict():
    # depth, batches, pipelineFillRatio, hostSyncCount, uploadOverlapMs,
    # consumerWaitMs, jitCacheHits/Misses); empty when the query ran
    # sequential
    pipeline: Dict[str, float] = field(default_factory=dict)
    # shuffle-wire summary of a distributed query (parallel/shuffle.py
    # ShuffleWireMetrics.summarize: exchanges, collectives, rowsMoved,
    # rowsUseful, bytesMoved, paddingRatio, slotOverflowRetries,
    # perColumnFallbacks); empty when the query never exchanged
    shuffle: Dict[str, float] = field(default_factory=dict)
    # query-level recovery ladder actions (robustness/driver.py
    # RecoveryAction events stamped with this query's id)
    recovery: List[Dict[str, str]] = field(default_factory=list)
    # watchdog trips/cancellations (robustness/watchdog.py
    # WatchdogTrip / WatchdogCancel events; "kind" is trip|cancel)
    watchdog: List[Dict[str, str]] = field(default_factory=list)
    # spill-integrity checksum failures (memory/spill.py
    # SpillCorruption events: tier, bufId, detail)
    corruption: List[Dict[str, str]] = field(default_factory=list)
    # stage-checkpoint lineage events (robustness/checkpoint.py
    # CheckpointWrite/Resume/Evict/Invalid; "kind" is
    # write|resume|evict|invalid)
    checkpoint: List[Dict[str, str]] = field(default_factory=list)
    # continuous-ingest events (robustness/incremental.py
    # StateCommit/StateRollback/StateEvict/IncrementalResume/
    # StateWatermark; "kind" is commit|rollback|evict|resume|
    # watermark) — resumes land here (they fire inside a tick's query
    # envelope); commit/rollback/watermark usually land on the app
    # (they fire between the tick's executions)
    incremental: List[Dict[str, str]] = field(default_factory=list)
    # full post-mortem trail of a fatally-failed query (QueryFatal:
    # error, recovery actions, watchdog + checkpoint snapshots) —
    # present even when the ladder never succeeded
    fatal: Dict[str, object] = field(default_factory=dict)
    # serving-layer admission cost (QueryEnd admission dict:
    # waitMs, weightBytes); empty when admission control is off
    admission: Dict[str, float] = field(default_factory=dict)
    # per-query budget ladder events (serving BudgetExhausted:
    # budget, used, limit, action=spill|reject)
    budget: List[Dict[str, str]] = field(default_factory=list)
    # whole-stage fusion + persistent jit cache (QueryEnd fusion dict,
    # exec/fusion.py: fusedStages/fusedOperators/dispatchesSaved/
    # fusibleChains + persistentHits/Misses/Invalid/Stores deltas)
    fusion: Dict[str, float] = field(default_factory=dict)
    # dropped persistent jit-cache entries (JitCacheInvalid events:
    # reason, entry) — informative; the query recompiled fresh
    jitcache: List[Dict[str, str]] = field(default_factory=list)
    # span-tracing rollup (QueryEnd spans dict, utils/tracing.py:
    # wallMs, exclusiveMs, unattributedMs/Frac, overlapMs, phases,
    # points, operators, sites); empty when tracing was off
    spans: Dict[str, object] = field(default_factory=dict)
    # cross-query reuse (QueryEnd sharing dict, serving/reuse.py +
    # serving/scheduler.py: resultCacheHit, resultCache
    # miss/invalidated note, spliceResumes/stageWrites tallies,
    # interleave wait/timeslices; stores ride the ResultCacheStore
    # EVENT — they land after the envelope closed); ABSENT when every
    # reuse knob is off
    sharing: Dict[str, object] = field(default_factory=dict)
    # result-cache / shared-stage-store events attributed to this
    # query (kind is hit|store|invalid|evict|write|splice)
    sharing_events: List[Dict[str, str]] = field(default_factory=list)
    # self-tuning cost model (QueryEnd planner dict,
    # plan/costmodel.py: decisions ledger [knob, site, chosen,
    # alternatives, predicted, observed], replans, mispredicts,
    # invalidLoads); ABSENT when costModel.enabled is off
    planner: Dict[str, object] = field(default_factory=dict)
    # CostModelInvalid events (corrupt evidence load / ledger write
    # fault — the model degraded to built-in defaults)
    costmodel: List[Dict[str, str]] = field(default_factory=list)
    # gray-failure counters (QueryEnd fleet dict,
    # robustness/grayfailure.py: hedgesFired/hedgesWon/
    # duplicatesSuppressed/suspects/quarantines/rejoins deltas +
    # suspectHosts list); ABSENT when grayFailure.enabled is off
    fleet_health: Dict[str, object] = field(default_factory=dict)

    @property
    def succeeded(self) -> bool:
        return self.status == "success"

    def op_names(self) -> List[str]:
        return [line.strip() for line in self.physical_plan.splitlines()]

    def fallback_ops(self) -> List[str]:
        return [op for op in self.op_names()
                if op.startswith("CpuFallbackExec")]

    def op_time_ns(self) -> Dict[str, int]:
        """Per-exec-node opTime; keys are metric-tree paths."""
        return {k: v.get("opTime", 0) for k, v in self.metrics.items()}


@dataclass
class AppInfo:
    session_id: str
    path: str
    conf: Dict[str, str] = field(default_factory=dict)
    queries: List[QueryInfo] = field(default_factory=list)
    start_ts: float = 0.0   # SessionStart record ts
    # recovery actions not attributable to a query (no qid yet when
    # the attempt died before its QueryStart)
    recovery: List[Dict[str, str]] = field(default_factory=list)
    # un-attributed watchdog / corruption / checkpoint / fatal events
    # (same reason)
    watchdog: List[Dict[str, str]] = field(default_factory=list)
    corruption: List[Dict[str, str]] = field(default_factory=list)
    checkpoint: List[Dict[str, str]] = field(default_factory=list)
    incremental: List[Dict[str, str]] = field(default_factory=list)
    fatal: List[Dict[str, object]] = field(default_factory=list)
    # serving-layer admission stream (Admission grants are emitted
    # before the query draws its id, so they live at session level)
    # and typed rejections (AdmissionReject: reason, waitMs)
    admission: List[Dict[str, float]] = field(default_factory=list)
    rejections: List[Dict[str, str]] = field(default_factory=list)
    # un-attributed BudgetExhausted events
    budget: List[Dict[str, str]] = field(default_factory=list)
    # un-attributed JitCacheInvalid events (a load outside any query
    # envelope)
    jitcache: List[Dict[str, str]] = field(default_factory=list)
    # un-attributed cross-query reuse events (a result-cache store
    # lands after its query's envelope closed, invalidations fire
    # during another query's lookup)
    sharing_events: List[Dict[str, str]] = field(default_factory=list)
    # un-attributed CostModelInvalid events (a load at session
    # construction runs before any query envelope)
    costmodel: List[Dict[str, str]] = field(default_factory=list)
    # fleet membership / fencing stream (HostJoin, HostLoss,
    # MeshShrink, FleetCacheFence) — host lifecycle is session-level
    # by nature, so these always live on the app
    fleet: List[Dict[str, object]] = field(default_factory=list)

    def max_concurrent(self) -> int:
        """Peak number of simultaneously-open query envelopes — the
        per-session concurrency timeline's headline number, computed
        from QueryStart/QueryEnd timestamps."""
        edges = []
        for q in self.queries:
            if q.start_ts and q.end_ts:
                edges.append((q.start_ts, 1))
                edges.append((q.end_ts, -1))
        peak = cur = 0
        for _, d in sorted(edges):
            cur += d
            peak = max(peak, cur)
        return peak

    @property
    def total_duration_ms(self) -> float:
        return sum(q.duration_ms for q in self.queries)

    @property
    def end_ts(self) -> float:
        return max((q.end_ts for q in self.queries if q.end_ts),
                   default=self.start_ts)


def parse_event_log(path: str) -> AppInfo:
    app = AppInfo(session_id=os.path.basename(path), path=path)
    open_queries: Dict[int, QueryInfo] = {}
    all_queries: Dict[int, QueryInfo] = {}  # incl. completed, last wins
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn write at the tail of a live log
            ev = rec.get("event")
            if ev == "SessionStart":
                app.conf = rec.get("conf", {})
                app.session_id = rec.get("sessionId", app.session_id)
                app.start_ts = rec.get("ts", 0.0)
            elif ev == "QueryStart":
                q = QueryInfo(rec["queryId"],
                              logical_plan=rec.get("logicalPlan", ""),
                              physical_plan=rec.get("physicalPlan", ""),
                              explain=rec.get("explain", ""),
                              start_ts=rec.get("ts", 0.0))
                open_queries[q.query_id] = q
                all_queries[q.query_id] = q
            elif ev == "RecoveryAction":
                # emitted AFTER the failed attempt's QueryEnd, so match
                # completed queries too; un-attributed actions go on the
                # app
                info = {k: rec[k] for k in ("action", "fault",
                                            "severity", "error", "rung")
                        if k in rec}
                q = all_queries.get(rec.get("queryId"))
                (q.recovery if q is not None
                 else app.recovery).append(info)
            elif ev in ("WatchdogTrip", "WatchdogCancel"):
                info = {k: rec[k] for k in
                        ("point", "deadlineMs", "elapsedMs",
                         "overrunMs") if k in rec}
                info["kind"] = "trip" if ev == "WatchdogTrip" \
                    else "cancel"
                q = all_queries.get(rec.get("queryId"))
                (q.watchdog if q is not None
                 else app.watchdog).append(info)
            elif ev == "SpillCorruption":
                info = {k: rec[k] for k in ("tier", "bufId", "detail")
                        if k in rec}
                q = all_queries.get(rec.get("queryId"))
                (q.corruption if q is not None
                 else app.corruption).append(info)
            elif ev in ("CheckpointWrite", "CheckpointResume",
                        "CheckpointEvict", "CheckpointInvalid"):
                info = {k: rec[k] for k in
                        ("stageId", "bytes", "stages", "stagesSaved",
                         "tier", "reason") if k in rec}
                info["kind"] = ev[len("Checkpoint"):].lower()
                q = all_queries.get(rec.get("queryId"))
                (q.checkpoint if q is not None
                 else app.checkpoint).append(info)
            elif ev in ("StateCommit", "StateRollback", "StateEvict",
                        "IncrementalResume", "StateWatermark",
                        "SinkCommit", "FleetRound"):
                info = {k: rec[k] for k in
                        ("epoch", "stateBytes", "entries", "mode",
                         "deltaFiles", "reusedState", "reason",
                         "bytes", "stageId", "stagesSaved",
                         "watermark", "evictedBuckets", "evictedRows",
                         "evictedBytes", "stateRows", "store",
                         "crc", "rows", "replayed", "round",
                         "subscribers", "sourcePulls", "splices",
                         "failures")
                        if k in rec}
                info["kind"] = {"StateCommit": "commit",
                                "StateRollback": "rollback",
                                "StateEvict": "evict",
                                "IncrementalResume": "resume",
                                "StateWatermark": "watermark",
                                "SinkCommit": "sink",
                                "FleetRound": "round"}[ev]
                q = all_queries.get(rec.get("queryId"))
                (q.incremental if q is not None
                 else app.incremental).append(info)
            elif ev == "Admission":
                app.admission.append(
                    {k: rec[k] for k in ("waitMs", "weightBytes",
                                         "active", "queued")
                     if k in rec})
            elif ev == "AdmissionReject":
                app.rejections.append(
                    {k: rec[k] for k in ("reason", "waitMs", "queued")
                     if k in rec})
            elif ev == "BudgetExhausted":
                info = {k: rec[k] for k in ("budget", "used", "limit",
                                            "action") if k in rec}
                q = all_queries.get(rec.get("queryId"))
                (q.budget if q is not None else app.budget).append(info)
            elif ev in ("ResultCacheHit", "ResultCacheStore",
                        "ResultCacheInvalid", "ResultCacheEvict",
                        "TemplateCacheHit", "TemplateCacheStore",
                        "SharedStageWrite", "SharedStageSplice",
                        "SharedStageEvict", "SharedStageInvalid"):
                info = {k: rec[k] for k in
                        ("key", "bytes", "batches", "rows", "reason",
                         "stageId", "stages", "stagesSaved", "tier",
                         "owner", "crossProcess") if k in rec}
                info["kind"] = {
                    "ResultCacheHit": "hit",
                    "ResultCacheStore": "store",
                    "ResultCacheInvalid": "invalid",
                    "ResultCacheEvict": "evict",
                    "TemplateCacheHit": "hit",
                    "TemplateCacheStore": "store",
                    "SharedStageWrite": "write",
                    "SharedStageSplice": "splice",
                    "SharedStageEvict": "evict",
                    "SharedStageInvalid": "invalid"}[ev]
                info["store"] = (
                    "template" if ev.startswith("Template") else
                    "result" if ev.startswith("Result") else "stage")
                q = all_queries.get(rec.get("queryId"))
                (q.sharing_events if q is not None
                 else app.sharing_events).append(info)
            elif ev in ("HostJoin", "HostLoss", "MeshShrink",
                        "FleetCacheFence", "HostSuspect",
                        "HostRecovered", "HostQuarantine", "HostRejoin",
                        "HedgeFired", "HedgeWon"):
                info = {k: rec[k] for k in
                        ("host", "pid", "hosts", "silentMs", "missed",
                         "fromHosts", "toHosts", "fromDevices",
                         "toDevices", "lostHosts", "reason", "action",
                         "key", "writerEpoch", "fenceEpoch", "ts",
                         "score", "factor", "point", "deadlineMs")
                        if k in rec}
                info["kind"] = {"HostJoin": "join",
                                "HostLoss": "loss",
                                "MeshShrink": "shrink",
                                "FleetCacheFence": "fence",
                                "HostSuspect": "suspect",
                                "HostRecovered": "recovered",
                                "HostQuarantine": "quarantine",
                                "HostRejoin": "rejoin",
                                "HedgeFired": "hedge_fired",
                                "HedgeWon": "hedge_won"}[ev]
                app.fleet.append(info)
            elif ev == "CostModelInvalid":
                info = {k: rec[k] for k in ("reason",) if k in rec}
                q = all_queries.get(rec.get("queryId"))
                (q.costmodel if q is not None
                 else app.costmodel).append(info)
            elif ev == "JitCacheInvalid":
                info = {k: rec[k] for k in ("reason", "entry")
                        if k in rec}
                q = all_queries.get(rec.get("queryId"))
                (q.jitcache if q is not None
                 else app.jitcache).append(info)
            elif ev == "QueryFatal":
                info = {k: rec[k] for k in
                        ("error", "recovery", "watchdog", "checkpoint")
                        if k in rec}
                q = all_queries.get(rec.get("queryId"))
                if q is not None:
                    q.fatal = info
                else:
                    app.fatal.append(info)
            elif ev == "QueryEnd":
                q = open_queries.pop(rec["queryId"],
                                     QueryInfo(rec["queryId"]))
                q.status = rec.get("status", "")
                q.duration_ms = rec.get("durationMs", 0.0)
                q.end_ts = rec.get("ts", 0.0)
                # distributed envelopes open before execution (so
                # mid-flight events attribute) and restate the final
                # explain at the end, once it is known
                q.explain = rec.get("explain") or q.explain
                q.metrics = rec.get("metrics", {})
                q.spill = rec.get("spill", {})
                q.retry = rec.get("retry", {})
                q.pipeline = rec.get("pipeline", {})
                q.shuffle = rec.get("shuffle", {})
                q.fusion = rec.get("fusion", {})
                q.spans = rec.get("spans", {}) or {}
                q.sharing = rec.get("sharing", {}) or {}
                q.planner = rec.get("planner", {}) or {}
                q.fleet_health = rec.get("fleet", {}) or {}
                q.admission = rec.get("admission", {}) or q.admission
                app.queries.append(q)
    # queries that started but never ended (crash) count as failed
    for q in open_queries.values():
        q.status = "incomplete"
        app.queries.append(q)
    return app


def load_logs(log_dir_or_file: str) -> List[AppInfo]:
    if os.path.isdir(log_dir_or_file):
        paths = sorted(glob.glob(os.path.join(log_dir_or_file,
                                              "tpu-events-*.jsonl")))
    elif os.path.isfile(log_dir_or_file):
        paths = [log_dir_or_file]
    else:
        return []
    return [parse_event_log(p) for p in paths]


def filter_apps(apps: List[AppInfo],
                match: Optional[str] = None,
                started_after: Optional[float] = None,
                newest: Optional[int] = None) -> List[AppInfo]:
    """The AppFilterImpl role: narrow a log directory's sessions by id
    regex, start time, and recency before analysis (reference
    tools/.../AppFilterImpl.scala)."""
    import re
    out = list(apps)
    if match:
        rx = re.compile(match)
        out = [a for a in out if rx.search(a.session_id) or
               rx.search(os.path.basename(a.path))]
    if started_after is not None:
        out = [a for a in out if a.start_ts >= started_after]
    if newest is not None and newest >= 0:
        out.sort(key=lambda a: -a.start_ts)
        out = out[:newest]
        out.sort(key=lambda a: a.start_ts)
    return out
