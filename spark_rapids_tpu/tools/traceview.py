"""Chrome-trace-event export + validation for the span runtime.

One file per query under ``spark.rapids.tpu.trace.dir``
(utils/tracing.py drains into :func:`write_trace` at QueryEnd).  The
format is the Chrome Trace Event JSON Object Format — open a file at
``ui.perfetto.dev`` (or chrome://tracing) and the query's operators,
exchanges, spills, and compiles render as nested slices per thread,
with the async exchange in-flight windows on their own track.

``validate_chrome_trace`` is the pure-python schema check the tests and
the premerge smoke gate on: no jsonschema dependency, just the format
contract (https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU).

CLI:  python -m spark_rapids_tpu.tools.traceview TRACE.json
      validates the file and prints the top exclusive-time slices.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict
from typing import Any, Dict, List, Optional

# the synthetic tid async in-flight windows render on (their wall time
# overlaps the dispatching thread's slices; Perfetto wants them on
# their own track)
ASYNC_TID = 1


def to_chrome_trace(records: List[tuple], qid: Optional[int] = None,
                    max_events: Optional[int] = None, dropped: int = 0,
                    wall_ms: float = 0.0,
                    status: str = "success") -> Dict[str, Any]:
    """Records (utils/tracing.py tuples) -> Chrome trace JSON object.

    Truncation contract: at most ``max_events`` "X" slices are
    emitted; anything beyond (plus buffer-side drops) is announced by
    an explicit ``trace-truncated`` instant event AND a top-level
    ``truncated`` count — a bounded trace must never silently read as
    a complete one."""
    from spark_rapids_tpu.utils import tracing as T
    pid = os.getpid()
    truncated = int(dropped)
    if max_events is not None and len(records) > max_events:
        truncated += len(records) - max_events
        records = records[:max_events]
    t0 = min((r[T.R_T0] for r in records), default=0)
    events: List[Dict[str, Any]] = []
    tids = {}
    for r in records:
        tid = ASYNC_TID if r[T.R_ASYNC] else r[T.R_TID]
        if not r[T.R_ASYNC]:
            tids.setdefault(tid, None)
        args: Dict[str, Any] = {}
        if r[T.R_OP]:
            args["op"] = r[T.R_OP]
        if r[T.R_SITE] is not None:
            site = r[T.R_SITE]
            args["site"] = site if isinstance(site, str) \
                else T.site_id(site)
        events.append({
            "name": r[T.R_OP] or r[T.R_POINT],
            "cat": T.phase_of(r[T.R_POINT]) if not r[T.R_ASYNC]
            else "async",
            "ph": "X",
            "ts": (r[T.R_T0] - t0) / 1e3,   # microseconds
            "dur": r[T.R_DUR] / 1e3,
            "pid": pid,
            "tid": tid,
            "args": args or {"point": r[T.R_POINT]},
        })
    meta: List[Dict[str, Any]] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": "spark-rapids-tpu" +
                         (f" q{qid}" if qid is not None else "")}}]
    for tid in sorted(tids):
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid, "args": {"name": f"thread-{tid}"}})
    meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                 "tid": ASYNC_TID,
                 "args": {"name": "async-exchange"}})
    if truncated:
        meta.append({"name": "trace-truncated", "ph": "i", "s": "g",
                     "ts": 0.0, "pid": pid, "tid": 0,
                     "args": {"dropped": truncated}})
    return {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {"queryId": qid, "status": status,
                      "wallMs": round(wall_ms, 3)},
        "truncated": truncated,
    }


def write_trace(records: List[tuple], path: str,
                qid: Optional[int] = None,
                max_events: Optional[int] = None, dropped: int = 0,
                wall_ms: float = 0.0, status: str = "success") -> str:
    obj = to_chrome_trace(records, qid=qid, max_events=max_events,
                          dropped=dropped, wall_ms=wall_ms,
                          status=status)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(obj, f)
    os.replace(tmp, path)
    return path


_VALID_PH = frozenset("BEXiIMCbnePFSTfsNODv(){}")


def validate_chrome_trace(obj: Any) -> List[str]:
    """Schema check against the Chrome trace-event object format.
    Returns a list of problems (empty = valid)."""
    problems: List[str] = []
    if not isinstance(obj, dict):
        return [f"top level must be an object, got {type(obj).__name__}"]
    events = obj.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents must be a list"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            problems.append(f"{where}: event must be an object")
            continue
        ph = ev.get("ph")
        if not isinstance(ph, str) or ph not in _VALID_PH:
            problems.append(f"{where}: bad phase {ph!r}")
            continue
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            problems.append(f"{where}: missing/empty name")
        for key in ("pid", "tid"):
            if key in ev and not isinstance(ev[key], int):
                problems.append(f"{where}: {key} must be an int")
        if ph in ("X", "B", "E", "i", "I"):
            ts = ev.get("ts")
            if not isinstance(ts, (int, float)) or ts < 0:
                problems.append(f"{where}: ts must be a number >= 0")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"{where}: X event needs dur >= 0")
        if "args" in ev and not isinstance(ev["args"], dict):
            problems.append(f"{where}: args must be an object")
    begins = sum(1 for e in events
                 if isinstance(e, dict) and e.get("ph") == "B")
    ends = sum(1 for e in events
               if isinstance(e, dict) and e.get("ph") == "E")
    if begins != ends:
        problems.append(f"unbalanced B/E events ({begins} vs {ends})")
    return problems


def load_trace(path: str) -> Dict[str, Any]:
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def summarize(obj: Dict[str, Any], top: int = 12) -> str:
    """Top slices by total duration per name — the quick look before
    opening Perfetto."""
    totals: Dict[str, float] = defaultdict(float)
    counts: Dict[str, int] = defaultdict(int)
    n = 0
    for ev in obj.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        n += 1
        totals[ev.get("name", "?")] += ev.get("dur", 0.0)
        counts[ev.get("name", "?")] += 1
    lines = [f"slices: {n}, truncated: {obj.get('truncated', 0)}, "
             f"query: {obj.get('otherData', {}).get('queryId')}"]
    lines.append(f"{'name':40s} {'total_ms':>10s} {'count':>7s}")
    for name in sorted(totals, key=lambda k: -totals[k])[:top]:
        lines.append(f"{name:40s} {totals[name] / 1e3:10.2f} "
                     f"{counts[name]:7d}")
    return "\n".join(lines)


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="spark_rapids_tpu.tools.traceview", description=__doc__)
    ap.add_argument("trace", help="exported trace JSON file")
    ap.add_argument("--top", type=int, default=12)
    args = ap.parse_args(argv)
    try:
        obj = load_trace(args.trace)
    except (OSError, json.JSONDecodeError) as e:
        print(f"cannot load {args.trace}: {e}", file=sys.stderr)
        return 1
    problems = validate_chrome_trace(obj)
    if problems:
        for p in problems:
            print(f"INVALID: {p}", file=sys.stderr)
        return 1
    print(summarize(obj, args.top))
    print("trace OK (load it at ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
