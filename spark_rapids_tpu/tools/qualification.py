"""Qualification tool: score workloads for TPU acceleration fitness.

CLI over engine event logs (no device needed) — the role of the
reference's qualification tool (tools/src/main/.../qualification/
QualificationMain.scala, QualAppInfo.scala): for each session it computes
how much of the work ran on TPU operators vs CPU fallbacks, surfaces the
reasons ops stayed on the CPU, and emits a ranked recommendation report
(text and CSV).

Usage:  python -m spark_rapids_tpu.tools.qualification LOGDIR [-o OUT.csv]
"""

from __future__ import annotations

import argparse
import csv
import re
import sys
from collections import Counter
from dataclasses import dataclass
from typing import Dict, List, Tuple

from spark_rapids_tpu.tools.eventlog import AppInfo, load_logs


@dataclass
class QualSummary:
    session_id: str
    num_queries: int
    failed_queries: int
    total_duration_ms: float
    tpu_op_time_share: float   # opTime on Tpu* execs / all opTime
    fallback_op_count: int
    not_on_tpu_reasons: Counter
    score: float               # 0..100 recommendation
    recommendation: str
    estimated_speedup: float = 1.0  # vs a CPU (pandas-class) run
    speedup_calibrated: bool = False  # measured weights vs builtin table
    # speedup evidence the engine actually measured (PR8/PR10 signals,
    # QueryEnd fusion + shuffle dicts): whole-stage fusion, encoded
    # execution, jit dispatches the fused stages saved, and device-wire
    # bytes the encoded wire shaved — concrete mechanisms behind the
    # estimate, not another model
    fused_stages: int = 0
    encoded_stages: int = 0
    dispatches_saved: int = 0
    encoded_bytes_saved: int = 0


_REASON_RE = re.compile(r"because (.+)$")

# exec metric name -> calibrated operator family (plan/cbo_weights.json,
# MEASURED by tools/cbo_calibrate.py — the role operatorsScore.csv plays
# for the reference's qualification estimates)
_EXEC_TO_OP = {
    "TpuHashAggregateExec": "Aggregate",
    "TpuFilterExec": "Filter",
    "TpuProjectExec": "Project",
    "TpuHashJoinExec": "Join",
    "TpuSortExec": "Sort",
    "TpuTopNExec": "Sort",
    "TpuWindowExec": "Window",
    "TpuGenerateExec": "Generate",
    "TpuExpandExec": "Project",
}


def _op_speedups() -> Tuple[Dict[str, float], bool]:
    """(cpu_cost/tpu_cost per operator family, calibrated?) — the flag
    distinguishes a calibration measured on THIS backend from the
    built-in ratio table so the report never claims measured numbers
    it does not have."""
    try:
        from spark_rapids_tpu.plan.cbo import (load_weights,
                                               weights_calibrated)
        tpu_w, cpu_w = load_weights()
        cal = weights_calibrated()
    except Exception:
        return {}, False
    return ({k: cpu_w[k] / tpu_w[k]
             for k in tpu_w if k in cpu_w and tpu_w[k] > 0}, cal)


def qualify_app(app: AppInfo) -> QualSummary:
    tpu_ns = 0
    cpu_ns = 0
    cpu_equiv_ns = 0.0  # estimated runtime of the same work on CPU
    fallbacks = 0
    reasons: Counter = Counter()
    failed = 0
    speedups, calibrated = _op_speedups()
    fused = encoded = saved = wire_saved = 0
    for q in app.queries:
        if not q.succeeded:
            failed += 1
        fu = q.fusion or {}
        fused += fu.get("fusedStages", 0)
        encoded += fu.get("encodedStages", 0)
        saved += fu.get("dispatchesSaved", 0)
        wire_saved += (q.shuffle or {}).get("encodedBytesSaved", 0)
        for path, m in q.metrics.items():
            name = path.rsplit(".", 1)[-1]
            # self time (exclusive of children) so nested ops don't
            # double count; older logs without it fall back to opTime
            t = m.get("opTimeSelf", m.get("opTime", 0))
            if name.startswith("CpuFallback"):
                cpu_ns += t
                cpu_equiv_ns += t  # already CPU
            else:
                tpu_ns += t
                cpu_equiv_ns += t * speedups.get(
                    _EXEC_TO_OP.get(name, ""), 1.0)
        fallbacks += len(q.fallback_ops())
        for line in q.explain.splitlines():
            mm = _REASON_RE.search(line)
            if mm:
                reasons[mm.group(1).strip()] += 1
    total = tpu_ns + cpu_ns
    # no op metrics at all (e.g. every query failed before running an
    # operator) means nothing ran on TPU — score it 0, not 100
    share = (tpu_ns / total) if total else 0.0
    # score: TPU-time share, penalized by failures (the reference weighs
    # SQL-task-time share and unsupported-op penalties similarly)
    score = 100.0 * share
    if app.queries:
        score *= 1.0 - 0.5 * (failed / len(app.queries))
    if score >= 80:
        rec = "Strongly Recommended"
    elif score >= 50:
        rec = "Recommended"
    elif score >= 20:
        rec = "Not Recommended"
    else:
        rec = "Not Applicable"
    est = (cpu_equiv_ns / total) if total else 1.0
    return QualSummary(app.session_id, len(app.queries), failed,
                       app.total_duration_ms, share, fallbacks, reasons,
                       score, rec, estimated_speedup=est,
                       speedup_calibrated=calibrated,
                       fused_stages=int(fused),
                       encoded_stages=int(encoded),
                       dispatches_saved=int(saved),
                       encoded_bytes_saved=int(wire_saved))


def format_report(summaries: List[QualSummary]) -> str:
    out = ["=" * 72,
           "TPU Qualification Report",
           "=" * 72]
    for s in sorted(summaries, key=lambda x: -x.score):
        out.append(f"\nSession: {s.session_id}")
        out.append(f"  queries: {s.num_queries}  failed: {s.failed_queries}"
                   f"  wall: {s.total_duration_ms:.0f} ms")
        out.append(f"  TPU op-time share: {s.tpu_op_time_share * 100:.1f}%"
                   f"  CPU-fallback ops: {s.fallback_op_count}")
        basis = ("measured per-op weights" if s.speedup_calibrated
                 else "builtin ratio table; run "
                      "spark-rapids-tpu-cbo-calibrate to measure")
        out.append(f"  estimated speedup vs CPU: "
                   f"{s.estimated_speedup:.2f}x ({basis})")
        if s.fused_stages or s.encoded_stages or s.dispatches_saved \
                or s.encoded_bytes_saved:
            out.append(
                f"  measured evidence: fusedStages={s.fused_stages} "
                f"encodedStages={s.encoded_stages} "
                f"dispatchesSaved={s.dispatches_saved} "
                f"encodedWireBytesSaved={s.encoded_bytes_saved}")
        out.append(f"  score: {s.score:.1f}  -> {s.recommendation}")
        for reason, n in s.not_on_tpu_reasons.most_common(5):
            out.append(f"    not-on-TPU ({n}x): {reason}")
    return "\n".join(out)


def write_csv(summaries: List[QualSummary], path: str) -> None:
    with open(path, "w", newline="", encoding="utf-8") as fh:
        w = csv.writer(fh)
        w.writerow(["session_id", "num_queries", "failed_queries",
                    "total_duration_ms", "tpu_op_time_share",
                    "fallback_op_count", "estimated_speedup",
                    "fused_stages", "encoded_stages",
                    "dispatches_saved", "encoded_bytes_saved", "score",
                    "recommendation"])
        for s in summaries:
            w.writerow([s.session_id, s.num_queries, s.failed_queries,
                        f"{s.total_duration_ms:.3f}",
                        f"{s.tpu_op_time_share:.4f}", s.fallback_op_count,
                        f"{s.estimated_speedup:.3f}",
                        s.fused_stages, s.encoded_stages,
                        s.dispatches_saved, s.encoded_bytes_saved,
                        f"{s.score:.2f}", s.recommendation])


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="spark_rapids_tpu.tools.qualification", description=__doc__)
    ap.add_argument("logdir", help="event-log directory or file")
    ap.add_argument("-o", "--output-csv", default=None)
    args = ap.parse_args(argv)
    apps = load_logs(args.logdir)
    if not apps:
        print("no event logs found", file=sys.stderr)
        return 1
    summaries = [qualify_app(a) for a in apps]
    print(format_report(summaries))
    if args.output_csv:
        write_csv(summaries, args.output_csv)
        print(f"\nwrote {args.output_csv}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
