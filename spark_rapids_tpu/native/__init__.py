"""ctypes bindings for the C++ host runtime (native/host_runtime.cpp).

The shared library is compiled on first use with g++ (cached next to the
source, keyed on mtime) — the framework stays importable and functional
without a toolchain: every facility here has a pure-Python fallback and
``available()`` gates the fast path.

Components (reference parity):
- ``HostArena``      — pinned-pool-style staging allocator
                       (GpuDeviceManager.scala:216 RMM pool analog)
- ``serialize_batch``/``deserialize_batch`` — columnar frame codec with
                       zero-RLE compression (JCudfSerialization +
                       TableCompressionCodec.scala analog)
- ``write_spill_file``/``read_spill_file`` — streamed spill pager
                       (RapidsDiskStore analog)
- ``FilePrefetcher``  — background-thread whole-file reader
                       (MultiFileCloudPartitionReader thread pool analog,
                       GpuParquetScan.scala:973)
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "host_runtime.cpp")
_LIB_DIR = os.path.join(_REPO_ROOT, "native", "build")
_LIB = os.path.join(_LIB_DIR, "libsparkrapids_host.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> bool:
    os.makedirs(_LIB_DIR, exist_ok=True)
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           _SRC, "-o", _LIB]
    try:
        res = subprocess.run(cmd, capture_output=True, timeout=120)
        return res.returncode == 0
    except (OSError, subprocess.TimeoutExpired):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            if not os.path.exists(_SRC):
                _load_failed = True
                return None
            if (not os.path.exists(_LIB) or
                    os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
                if not _build():
                    _load_failed = True
                    return None
            lib = ctypes.CDLL(_LIB)
        except OSError:
            _load_failed = True
            return None
        _declare(lib)
        _lib = lib
        return _lib


def _declare(lib: ctypes.CDLL) -> None:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.arena_create.restype = ctypes.c_void_p
    lib.arena_create.argtypes = [ctypes.c_size_t]
    lib.arena_alloc.restype = ctypes.c_void_p
    lib.arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
    lib.arena_free.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                               ctypes.c_size_t]
    lib.arena_stats.argtypes = [ctypes.c_void_p] + \
        [ctypes.POINTER(ctypes.c_size_t)] * 3
    lib.arena_destroy.argtypes = [ctypes.c_void_p]

    lib.frame_serialize.restype = ctypes.c_void_p
    lib.frame_serialize.argtypes = [
        ctypes.c_uint64, ctypes.c_uint32, ctypes.POINTER(u8p),
        ctypes.POINTER(ctypes.c_uint64), u8p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint64)]
    lib.frame_data.restype = u8p
    lib.frame_data.argtypes = [ctypes.c_void_p]
    lib.frame_release.argtypes = [ctypes.c_void_p]
    lib.frame_header.restype = ctypes.c_int
    lib.frame_header.argtypes = [
        u8p, ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint64),
        u8p, ctypes.c_uint32]
    lib.frame_deserialize.restype = ctypes.c_int
    lib.frame_deserialize.argtypes = [
        u8p, ctypes.c_uint64, ctypes.POINTER(u8p),
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint32, ctypes.c_int]

    lib.pager_write.restype = ctypes.c_int64
    lib.pager_write.argtypes = [ctypes.c_char_p, u8p, ctypes.c_uint64]
    lib.pager_read.restype = ctypes.c_int64
    lib.pager_read.argtypes = [ctypes.c_char_p, u8p, ctypes.c_uint64]
    lib.pager_file_size.restype = ctypes.c_int64
    lib.pager_file_size.argtypes = [ctypes.c_char_p]

    lib.prefetcher_create.restype = ctypes.c_void_p
    lib.prefetcher_create.argtypes = [ctypes.c_int]
    lib.prefetcher_submit.restype = ctypes.c_int
    lib.prefetcher_submit.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p), ctypes.c_int]
    lib.prefetcher_wait.restype = ctypes.c_int64
    lib.prefetcher_wait.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.prefetcher_data.restype = u8p
    lib.prefetcher_data.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.prefetcher_release.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.prefetcher_destroy.argtypes = [ctypes.c_void_p]


def available() -> bool:
    return _load() is not None


# ------------------------------------------------------------------ arena --

class HostArena:
    """Staging-buffer arena; returns numpy views over arena memory."""

    def __init__(self, slab_bytes: int = 64 << 20):
        lib = _load()
        self._lib = lib
        self._handle = lib.arena_create(slab_bytes) if lib else None
        self._live: Dict[int, Tuple[int, int]] = {}

    @property
    def native(self) -> bool:
        return self._handle is not None

    def alloc(self, nbytes: int) -> np.ndarray:
        if self._handle is None:
            return np.empty(nbytes, dtype=np.uint8)  # fallback: plain numpy
        ptr = self._lib.arena_alloc(self._handle, nbytes)
        if not ptr:
            raise MemoryError(f"arena_alloc({nbytes}) failed")
        buf = (ctypes.c_uint8 * nbytes).from_address(ptr)
        # The view chain arr -> buf -> arena keeps the slabs alive even if
        # the caller drops the HostArena while views are outstanding
        # (ctypes instances accept attribute assignment).
        buf._arena_keepalive = self
        arr = np.frombuffer(buf, dtype=np.uint8)
        self._live[arr.__array_interface__["data"][0]] = (ptr, nbytes)
        return arr

    def free(self, arr: np.ndarray) -> None:
        if self._handle is None:
            return
        key = arr.__array_interface__["data"][0]
        ptr, nbytes = self._live.pop(key)
        self._lib.arena_free(self._handle, ptr, nbytes)

    def stats(self) -> Dict[str, int]:
        if self._handle is None:
            return {"reserved": 0, "allocated": 0, "watermark": 0}
        r = ctypes.c_size_t()
        a = ctypes.c_size_t()
        w = ctypes.c_size_t()
        self._lib.arena_stats(self._handle, ctypes.byref(r), ctypes.byref(a),
                              ctypes.byref(w))
        return {"reserved": r.value, "allocated": a.value,
                "watermark": w.value}

    def close(self) -> None:
        if self._handle is None:
            return
        if self._live:
            # freeing the slabs would leave the outstanding numpy views
            # dangling (silent memory corruption on later access)
            raise RuntimeError(
                f"HostArena.close with {len(self._live)} live allocations")
        self._lib.arena_destroy(self._handle)
        self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            # live views hold a keepalive reference to this arena, so
            # reaching __del__ with _live non-empty cannot happen; any
            # other failure here just leaks the arena
            pass


# ------------------------------------------------------- frame serializer --

# numpy-dtype-agnostic: the frame stores raw little-endian bytes plus a
# dtype code the Python layer maps back (codes below; strings ride as uint8
# chars + int32 offsets).  Codes are part of the on-disk/wire format — do
# not renumber.

DTYPE_CODES = {
    "boolean": 1, "tinyint": 2, "smallint": 3, "int": 4, "bigint": 5,
    "float": 6, "double": 7, "string": 8, "date": 9, "timestamp": 10,
}
CODE_TO_DTYPE = {v: k for k, v in DTYPE_CODES.items()}


def dtype_code(dt) -> int:
    """Frame dtype code for a framework DataType (0 = unknown/opaque)."""
    return DTYPE_CODES.get(getattr(dt, "name", str(dt)), 0)

def _as_bytes(a: Optional[np.ndarray]) -> Optional[np.ndarray]:
    if a is None:
        return None
    return np.ascontiguousarray(a).view(np.uint8).reshape(-1)


# frame codec level for compress=True: 0 raw, 1 zrle only, 2 zrle+lzb
# (per-buffer, smaller wins); set from spark.rapids.shuffle.compression.codec
_frame_codec_level = 2


def codec_level(name: str) -> int:
    """Conf codec name -> native frame codec level.  "zstd" is accepted
    as an alias of the strongest level for config compatibility with
    the reference's codec names."""
    levels = {"none": 0, "zrle": 1, "lz4": 2, "zstd": 2}
    if name not in levels:
        raise ValueError(f"unknown compression codec {name!r}")
    return levels[name]


def set_frame_codec(name: str) -> None:
    """Set the PROCESS-default level (used when compress=True).
    Sessions scope their conf codec per-catalog instead — see
    SpillableBatchCatalog.frame_codec."""
    global _frame_codec_level
    _frame_codec_level = codec_level(name)


def frame_codec_level() -> int:
    return _frame_codec_level


def serialize_batch(nrows: int,
                    columns: Sequence[Tuple[int, Optional[np.ndarray],
                                            Optional[np.ndarray],
                                            Optional[np.ndarray]]],
                    compress=True) -> bytes:
    """columns: (dtype_code, data, validity, offsets) per column.
    ``compress``: True = process-default level, False = raw, or an
    explicit int level (0 raw / 1 zrle / 2 zrle+lzb)."""
    lib = _load()
    flat: List[Optional[np.ndarray]] = []
    for _, data, validity, offsets in columns:
        flat += [_as_bytes(data), _as_bytes(validity), _as_bytes(offsets)]
    if lib is None:
        return _py_serialize(nrows, columns)
    ncols = len(columns)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    bufs = (u8p * (3 * ncols))()
    lens = (ctypes.c_uint64 * (3 * ncols))()
    keepalive = []
    for i, a in enumerate(flat):
        if a is None or a.size == 0:
            bufs[i] = None
            lens[i] = 0
        else:
            keepalive.append(a)
            bufs[i] = a.ctypes.data_as(u8p)
            lens[i] = a.nbytes
    codes = (ctypes.c_uint8 * ncols)(*[c[0] for c in columns])
    out_len = ctypes.c_uint64()
    if compress is True:
        level = _frame_codec_level
    elif compress is False:
        level = 0
    else:
        level = int(compress)
    frame = lib.frame_serialize(nrows, ncols, bufs, lens, codes,
                                level, ctypes.byref(out_len))
    try:
        data_ptr = lib.frame_data(frame)
        return ctypes.string_at(data_ptr, out_len.value)
    finally:
        lib.frame_release(frame)


def deserialize_batch(blob: bytes, max_cols: int = 4096
                      ) -> Tuple[int, List[Tuple[int, Optional[np.ndarray],
                                                 Optional[np.ndarray],
                                                 Optional[np.ndarray]]]]:
    """Returns (nrows, [(dtype_code, data_u8, validity_u8, offsets_u8)]).
    Buffers come back as raw uint8; the caller reinterprets via dtype_code."""
    lib = _load()
    if lib is None:
        return _py_deserialize(blob)
    src = np.frombuffer(blob, dtype=np.uint8)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    srcp = src.ctypes.data_as(u8p)
    nrows = ctypes.c_uint64()
    ncols = ctypes.c_uint32()
    lens = (ctypes.c_uint64 * (3 * max_cols))()
    codes = (ctypes.c_uint8 * max_cols)()
    off = lib.frame_header(srcp, len(blob), ctypes.byref(nrows),
                           ctypes.byref(ncols), lens, codes, max_cols)
    if off < 0:
        raise ValueError(f"bad frame (err {off})")
    nc = ncols.value
    outs: List[Optional[np.ndarray]] = []
    dst = (u8p * (3 * nc))()
    for i in range(3 * nc):
        n = lens[i]
        if n == 0:
            outs.append(None)
            dst[i] = None
        else:
            a = np.empty(n, dtype=np.uint8)
            outs.append(a)
            dst[i] = a.ctypes.data_as(u8p)
    rc = lib.frame_deserialize(srcp, len(blob), dst, lens, nc, off)
    if rc != 0:
        raise ValueError(f"frame payload corrupt (err {rc})")
    cols = [(codes[c], outs[c * 3], outs[c * 3 + 1], outs[c * 3 + 2])
            for c in range(nc)]
    return nrows.value, cols


def _py_serialize(nrows, columns) -> bytes:
    import pickle
    payload = [(code,
                None if d is None else np.ascontiguousarray(d),
                None if v is None else np.ascontiguousarray(v),
                None if o is None else np.ascontiguousarray(o))
               for code, d, v, o in columns]
    return b"PYF1" + pickle.dumps((nrows, payload))


def _py_deserialize(blob: bytes):
    import pickle
    if blob[:4] == b"PYF1":
        nrows, payload = pickle.loads(blob[4:])
        cols = [(code, _as_bytes(d), _as_bytes(v), _as_bytes(o))
                for code, d, v, o in payload]
        return nrows, cols
    raise ValueError("native frame present but native library unavailable")


# ------------------------------------------------------------ spill pager --

def write_spill_file(path: str, blob: bytes) -> int:
    lib = _load()
    if lib is None:
        with open(path, "wb") as f:
            f.write(blob)
        return len(blob)
    src = np.frombuffer(blob, dtype=np.uint8)
    n = lib.pager_write(path.encode(), src.ctypes.data_as(
        ctypes.POINTER(ctypes.c_uint8)), len(blob))
    if n < 0:
        raise IOError(f"pager_write({path}) failed: {n}")
    return int(n)


def read_spill_file(path: str) -> bytes:
    lib = _load()
    if lib is None:
        with open(path, "rb") as f:
            return f.read()
    size = lib.pager_file_size(path.encode())
    if size < 0:
        raise FileNotFoundError(path)
    dst = np.empty(size, dtype=np.uint8)
    n = lib.pager_read(path.encode(), dst.ctypes.data_as(
        ctypes.POINTER(ctypes.c_uint8)), size)
    if n != size:
        raise IOError(f"pager_read({path}) short read: {n} of {size}")
    return dst.tobytes()


# ------------------------------------------------------------- prefetcher --

class FilePrefetcher:
    """Background whole-file reads; files become available as they finish,
    overlapping host IO with device decode (the MULTITHREADED reader
    strategy)."""

    def __init__(self, nthreads: int = 4):
        lib = _load()
        self._lib = lib
        self._handle = lib.prefetcher_create(nthreads) if lib else None
        self._paths: List[str] = []
        self._pool = None
        self._futures = []
        if self._handle is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(max_workers=nthreads)

    @property
    def native(self) -> bool:
        return self._handle is not None

    def submit(self, paths: Sequence[str]) -> None:
        base = len(self._paths)
        self._paths.extend(paths)
        if self._handle is not None:
            arr = (ctypes.c_char_p * len(paths))(
                *[p.encode() for p in paths])
            self._lib.prefetcher_submit(self._handle, arr, len(paths))
        else:
            def read(p):
                with open(p, "rb") as f:
                    return f.read()
            self._futures.extend(self._pool.submit(read, p) for p in paths)
            del base

    def get(self, idx: int) -> bytes:
        if self._handle is not None:
            n = self._lib.prefetcher_wait(self._handle, idx)
            if n < 0:
                raise IOError(f"prefetch of {self._paths[idx]} failed")
            ptr = self._lib.prefetcher_data(self._handle, idx)
            out = ctypes.string_at(ptr, n)
            self._lib.prefetcher_release(self._handle, idx)
            return out
        return self._futures[idx].result()

    def close(self) -> None:
        if self._handle is not None:
            self._lib.prefetcher_destroy(self._handle)
            self._handle = None
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
