"""Device-resident column: the TPU counterpart of GpuColumnVector.

Reference: ``sql-plugin/src/main/java/com/nvidia/spark/rapids/GpuColumnVector.java:46``
wraps a device cudf ColumnVector with dynamic length.  XLA wants static shapes,
so a TPU Column is a *fixed-capacity* device array plus a host-side logical row
count:

* capacity is bucketed to powers of two (min 1024) so the universe of traced
  shapes — and therefore XLA recompiles — stays bounded;
* rows in ``[nrows, capacity)`` are padding with unspecified contents; any
  row-sensitive kernel (aggregate, sort, compaction, collect) masks them with
  ``iota < nrows``;
* null tracking is a separate bool validity array (True = valid), ``None``
  meaning "no nulls" — the dense equivalent of cudf's validity bitmask.

Strings are a pair of fixed-capacity arrays (int32 offsets[capacity+1] +
uint8 chars[char_capacity]) mirroring Arrow/cudf layout but padded.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.dtypes import DataType

MIN_CAPACITY = 1024


def bucket_capacity(n: int, minimum: int = MIN_CAPACITY) -> int:
    """Round up to the shape bucket: next power of two, floor ``minimum``."""
    n = max(int(n), 1)
    cap = minimum
    while cap < n:
        cap <<= 1
    return cap


class RowCount:
    """Lazy, possibly device-resident row count.

    The per-batch ``int(n)`` on an aggregation's group count costs a
    full tunnel round trip (device->host sync) — the dominant
    serialization in the r05 group-by bench.  A RowCount carries the
    count as a device scalar through the batch pipeline and only
    materializes (``int(rc)``) at true host decision points; the
    materialized value is cached, so one RowCount never syncs twice.

    ``materialize_all`` resolves many RowCounts in ONE device transfer
    (one counted sync) — the end-of-query metric resolution path.
    """

    __slots__ = ("_value", "_device", "_device_i32")

    def __init__(self, value=None, device=None):
        if value is None and device is None:
            raise ValueError("RowCount needs a value or a device scalar")
        self._value = None if value is None else int(value)
        self._device = device
        self._device_i32 = None

    @property
    def is_concrete(self) -> bool:
        return self._value is not None

    def __int__(self) -> int:
        if self._value is None:
            from spark_rapids_tpu.utils import hostsync
            hostsync.count_sync()
            self._value = int(np.asarray(self._device))
        return self._value

    __index__ = __int__

    def device_i32(self):
        """The count as an int32 device scalar (no sync)."""
        if self._device_i32 is None:
            import jax.numpy as jnp
            if self._device is not None:
                d = self._device
                self._device_i32 = d if d.dtype == jnp.int32 \
                    else d.astype(jnp.int32)
            else:
                self._device_i32 = jnp.int32(self._value)
        return self._device_i32

    @staticmethod
    def wrap(n) -> "RowCount":
        if isinstance(n, RowCount):
            return n
        return RowCount(value=int(n))

    @staticmethod
    def materialize_all(counts) -> None:
        """Resolve every unmaterialized RowCount in ``counts`` with one
        batched device fetch (one counted sync)."""
        from spark_rapids_tpu.utils import hostsync
        lazy = [rc for rc in counts
                if isinstance(rc, RowCount) and rc._value is None]
        if not lazy:
            return
        values = hostsync.fetch_all([rc._device for rc in lazy])
        for rc, v in zip(lazy, values):
            rc._value = int(v)

    def __repr__(self) -> str:
        if self._value is not None:
            return f"RowCount({self._value})"
        return "RowCount(<device>)"


class Column:
    """One device column with logical length ``nrows`` and static capacity.

    Buffers are HOST-LAZY: a column built from host data keeps the exact
    numpy arrays and materializes the device (jax) copy only when a
    device consumer touches ``.data``/``.validity``/``.offsets``.  On
    real TPU hardware f64 is emulated (~48-bit mantissa), so an eager
    host->device->host round trip silently perturbs doubles by ~1e-16 —
    enough to flip boundary comparisons (0.05 >= 0.05) on any host-side
    consumer (CPU fallback, writers, to_pandas).  Host-side export paths
    therefore read ``host_values()`` and never touch the device."""

    __slots__ = ("dtype", "_np_data", "_jax_data", "_np_validity",
                 "_jax_validity", "_np_offsets", "_jax_offsets",
                 "_row_count", "dictionary")

    def __init__(self, dtype: DataType, data, nrows,
                 validity=None, offsets=None, dictionary=None):
        self.dtype = dtype
        # fixed-width values, or uint8 chars for string
        self._np_data = data if isinstance(data, np.ndarray) else None
        self._jax_data = None if self._np_data is not None else data
        # bool[capacity] or None (all valid)
        self._np_validity = validity if isinstance(validity, np.ndarray) \
            else None
        self._jax_validity = None if self._np_validity is not None \
            else validity
        # int32[capacity+1] for strings else None
        self._np_offsets = offsets if isinstance(offsets, np.ndarray) \
            else None
        self._jax_offsets = None if self._np_offsets is not None \
            else offsets
        self.dictionary = dictionary  # host list[str] when elements are
        #                               dictionary codes (array<string>)
        self._row_count = RowCount.wrap(nrows)
        if dtype.has_offsets and self._np_offsets is None and \
                self._jax_offsets is None:
            raise ValueError(f"{dtype} column requires offsets")

    @property
    def nrows(self) -> int:
        """Concrete row count (syncs once if carried lazily on device)."""
        return int(self._row_count)

    @nrows.setter
    def nrows(self, n) -> None:
        self._row_count = RowCount.wrap(n)

    @property
    def row_count(self) -> RowCount:
        """The possibly-lazy count; use ``row_count.device_i32()`` on
        device paths to avoid forcing a host sync."""
        return self._row_count

    # -------------------------------------------------------- buffer access --
    def _upload(self, np_buf):
        """Host->device materialization (once per buffer).  Timed into
        the pipeline's upload-overlap accounting when this thread is a
        pipeline worker (utils/hostsync.watch_uploads)."""
        import time
        from spark_rapids_tpu.utils import hostsync
        t0 = time.perf_counter_ns()
        out = jnp.asarray(np_buf)
        hostsync.note_upload(time.perf_counter_ns() - t0)
        return out

    @property
    def data(self):
        """Device view of the value buffer (materialized on demand)."""
        if self._jax_data is None:
            self._jax_data = self._upload(self._np_data)
        return self._jax_data

    @property
    def validity(self):
        if self._jax_validity is None:
            if self._np_validity is None:
                return None
            self._jax_validity = self._upload(self._np_validity)
        return self._jax_validity

    @property
    def offsets(self):
        if self._jax_offsets is None:
            if self._np_offsets is None:
                return None
            self._jax_offsets = self._upload(self._np_offsets)
        return self._jax_offsets

    def host_values(self) -> np.ndarray:
        """Exact host view of the full value buffer: the original numpy
        when the column was built from host data (bit-exact), else a
        device fetch."""
        if self._np_data is not None:
            return self._np_data
        from spark_rapids_tpu.utils import hostsync
        hostsync.count_sync()
        return np.asarray(self._jax_data)

    def host_validity(self) -> Optional[np.ndarray]:
        if self._np_validity is not None:
            return self._np_validity
        if self._jax_validity is None:
            return None
        from spark_rapids_tpu.utils import hostsync
        hostsync.count_sync()
        return np.asarray(self._jax_validity)

    def host_offsets(self) -> Optional[np.ndarray]:
        if self._np_offsets is not None:
            return self._np_offsets
        if self._jax_offsets is None:
            return None
        from spark_rapids_tpu.utils import hostsync
        hostsync.count_sync()
        return np.asarray(self._jax_offsets)

    # ------------------------------------------------------------------ shape --
    @property
    def capacity(self) -> int:
        if self.dtype.has_offsets:
            off = self._np_offsets if self._np_offsets is not None \
                else self._jax_offsets
            return int(off.shape[0]) - 1
        d = self._np_data if self._np_data is not None else self._jax_data
        return int(d.shape[0])

    @property
    def char_capacity(self) -> int:
        """Element-buffer capacity (chars for strings, elements for
        arrays)."""
        assert self.dtype.has_offsets
        d = self._np_data if self._np_data is not None else self._jax_data
        return int(d.shape[0])

    @property
    def has_nulls(self) -> bool:
        return self._np_validity is not None or \
            self._jax_validity is not None

    def null_count(self) -> int:
        if not self.has_nulls:
            return 0
        v = self.host_validity()[: self.nrows]
        return int((~v).sum())

    def device_size_bytes(self) -> int:
        d = self._np_data if self._np_data is not None else self._jax_data
        n = d.size * d.dtype.itemsize
        if self.has_nulls:
            v = self._np_validity if self._np_validity is not None \
                else self._jax_validity
            n += v.size
        off = self._np_offsets if self._np_offsets is not None \
            else self._jax_offsets
        if off is not None:
            n += off.size * 4
        return int(n)

    # ----------------------------------------------------------- construction --
    @classmethod
    def from_numpy(cls, values: np.ndarray, dtype: Optional[DataType] = None,
                   validity: Optional[np.ndarray] = None,
                   capacity: Optional[int] = None) -> "Column":
        """Build a device column from host values (non-string)."""
        values = np.asarray(values)
        if values.dtype.kind == "O":
            import datetime as _dt
            sample = next((v for v in values if v is not None), None)
            if isinstance(sample, _dt.datetime):
                validity = np.array([v is not None for v in values]) \
                    if validity is None else validity
                filled = [sample if v is None else v for v in values]
                values = np.array(filled, dtype="datetime64[us]")
                dtype = dtype or dts.TIMESTAMP_US
            elif isinstance(sample, _dt.date):
                validity = np.array([v is not None for v in values]) \
                    if validity is None else validity
                filled = [sample if v is None else v for v in values]
                values = np.array(filled, dtype="datetime64[D]").astype(
                    np.int32)
                dtype = dtype or dts.DATE32
        if values.dtype.kind in ("U", "S", "O"):
            return cls.from_strings(values.tolist(), validity=validity,
                                    capacity=capacity)
        if validity is not None and np.asarray(validity).all():
            validity = None
        if values.dtype.kind == "M":
            values = values.astype("datetime64[us]").astype(np.int64)
            dtype = dtype or dts.TIMESTAMP_US
        dtype = dtype or dts.from_numpy_dtype(values.dtype)
        nrows = len(values)
        cap = capacity or bucket_capacity(nrows)
        buf = np.zeros(cap, dtype=dtype.storage)
        buf[:nrows] = values.astype(dtype.storage, copy=False)
        dev_validity = None
        if validity is not None:
            v = np.zeros(cap, dtype=np.bool_)
            v[:nrows] = validity
            if not v[:nrows].all():
                dev_validity = v
        return cls(dtype, buf, nrows, validity=dev_validity)

    @classmethod
    def from_strings(cls, values: Sequence[Optional[str]],
                     validity: Optional[np.ndarray] = None,
                     capacity: Optional[int] = None,
                     char_capacity: Optional[int] = None) -> "Column":
        nrows = len(values)
        valid = np.ones(nrows, dtype=np.bool_)
        if validity is not None:
            valid &= np.asarray(validity, dtype=np.bool_)
        encoded = []
        for i, s in enumerate(values):
            if s is None:
                valid[i] = False
                encoded.append(b"")
            else:
                encoded.append(str(s).encode("utf-8"))
        offsets = np.zeros(nrows + 1, dtype=np.int32)
        np.cumsum([len(b) for b in encoded], out=offsets[1:] if nrows else None)
        total = int(offsets[-1]) if nrows else 0
        chars = np.frombuffer(b"".join(encoded), dtype=np.uint8) if total else \
            np.zeros(0, dtype=np.uint8)
        cap = capacity or bucket_capacity(nrows)
        ccap = char_capacity or bucket_capacity(max(total, 1))
        off_buf = np.zeros(cap + 1, dtype=np.int32)
        off_buf[: nrows + 1] = offsets
        off_buf[nrows + 1:] = offsets[-1] if nrows else 0
        char_buf = np.zeros(ccap, dtype=np.uint8)
        char_buf[:total] = chars
        dev_validity = None
        if not valid.all():
            v = np.zeros(cap, dtype=np.bool_)
            v[:nrows] = valid
            dev_validity = v
        return cls(dts.STRING, char_buf, nrows,
                   validity=dev_validity, offsets=off_buf)

    @classmethod
    def from_arrays(cls, values, element: DataType,
                    validity: Optional[np.ndarray] = None,
                    capacity: Optional[int] = None,
                    elem_capacity: Optional[int] = None) -> "Column":
        """Array column from a list of (list | None): flat element buffer +
        int32 offsets, the string chars layout generalized to any
        fixed-width element type.  Null ELEMENTS inside arrays are not
        supported (the planner tags them off)."""
        nrows = len(values)
        valid = np.ones(nrows, dtype=np.bool_)
        if validity is not None:
            valid &= np.asarray(validity, dtype=np.bool_)
        rows = []
        for i, v in enumerate(values):
            if v is None:
                valid[i] = False
                rows.append([])
            elif any(e is None for e in v):
                raise ValueError("null array elements not supported")
            else:
                rows.append(list(v))
        lens = np.array([len(r) for r in rows], dtype=np.int32)
        offsets = np.zeros(nrows + 1, dtype=np.int32)
        np.cumsum(lens, out=offsets[1:] if nrows else None)
        total = int(offsets[-1]) if nrows else 0
        dictionary = None
        if element.is_string:
            # variable-width elements: store int32 dictionary codes with a
            # host-side string table (array<string> is a host-surface type)
            flat_strs = [e for r in rows for e in r]
            dictionary = sorted(set(flat_strs))
            code = {s: i for i, s in enumerate(dictionary)}
            flat = np.array([code[s] for s in flat_strs], dtype=np.int32) \
                if total else np.zeros(0, dtype=np.int32)
            storage = np.dtype(np.int32)
        else:
            flat = np.array([e for r in rows for e in r],
                            dtype=element.storage) if total else \
                np.zeros(0, dtype=element.storage)
            storage = element.storage
        cap = capacity or bucket_capacity(nrows)
        ecap = elem_capacity or bucket_capacity(max(total, 1))
        off_buf = np.zeros(cap + 1, dtype=np.int32)
        off_buf[: nrows + 1] = offsets
        off_buf[nrows + 1:] = offsets[-1] if nrows else 0
        elem_buf = np.zeros(ecap, dtype=storage)
        elem_buf[:total] = flat
        dev_validity = None
        if not valid.all():
            v = np.zeros(cap, dtype=np.bool_)
            v[:nrows] = valid
            dev_validity = v
        if element.is_string:
            from spark_rapids_tpu.ops.json_ops import ARRAY_STRING
            adt = ARRAY_STRING
        else:
            from spark_rapids_tpu.columnar.dtypes import ArrayType
            adt = ArrayType(element)
        return cls(adt, elem_buf, nrows,
                   validity=dev_validity, offsets=off_buf,
                   dictionary=dictionary)

    @classmethod
    def from_arrow(cls, arr, capacity: Optional[int] = None) -> "Column":
        import pyarrow as pa
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        if pa.types.is_dictionary(arr.type):
            arr = arr.dictionary_decode()
        dtype = dts.from_arrow_type(arr.type)
        if dtype.is_string:
            return cls.from_strings(arr.to_pylist(), capacity=capacity)
        if dtype.is_array:
            return cls.from_arrays(arr.to_pylist(), dtype.element,
                                   capacity=capacity)
        validity = None
        if arr.null_count:
            validity = ~np.asarray(arr.is_null())
        if dtype.is_decimal:
            ints = [None if v is None else int(v.scaleb(dtype.scale))
                    for v in arr.to_pylist()]
            values = np.array([0 if v is None else v for v in ints],
                              dtype=np.int64)
        elif dtype.is_timestamp:
            ints = arr.cast(pa.timestamp("us")).cast(pa.int64())
            values = np.asarray(ints.fill_null(0))
        elif dtype.is_date:
            values = np.asarray(arr.cast(pa.int32()).fill_null(0))
        else:
            np_arr = arr.to_numpy(zero_copy_only=False)
            if arr.null_count:
                # to_numpy promotes ints-with-nulls to float NaN; zero the
                # null slots before casting back to the storage dtype.
                np_arr = np.where(validity, np_arr, 0)
            values = np_arr.astype(dtype.storage, copy=False)
        return cls.from_numpy(values, dtype=dtype, validity=validity,
                              capacity=capacity)

    # ------------------------------------------------------------- host export --
    def to_numpy(self) -> np.ndarray:
        """Valid-length values as numpy; nulls hold unspecified data.
        Reads the exact host buffer when one exists (never a device
        round trip — see class docstring)."""
        if self.dtype.is_string:
            raise TypeError("use to_pylist for string columns")
        return self.host_values()[: self.nrows]

    def validity_numpy(self) -> np.ndarray:
        v = self.host_validity()
        if v is None:
            return np.ones(self.nrows, dtype=np.bool_)
        return v[: self.nrows]

    def to_pylist(self):
        valid = self.validity_numpy()
        if self.dtype.is_array:
            offs = self.host_offsets()[: self.nrows + 1]
            elems = self.host_values()
            edt = self.dtype.element
            if self.dictionary is not None:
                table = self.dictionary
                return [[table[int(v)] for v in elems[offs[i]:offs[i + 1]]]
                        if valid[i] else None for i in range(self.nrows)]
            def conv(x):
                if edt.is_boolean:
                    return bool(x)
                if edt.is_floating:
                    return float(x)
                return int(x)
            return [[conv(v) for v in elems[offs[i]:offs[i + 1]]]
                    if valid[i] else None for i in range(self.nrows)]
        if self.dtype.is_string:
            offs = self.host_offsets()[: self.nrows + 1]
            chars = self.host_values()
            blob = chars.tobytes()
            return [blob[offs[i]:offs[i + 1]].decode("utf-8")
                    if valid[i] else None for i in range(self.nrows)]
        vals = self.to_numpy()
        out = []
        for i in range(self.nrows):
            if not valid[i]:
                out.append(None)
            elif self.dtype.is_decimal:
                import decimal
                out.append(decimal.Decimal(int(vals[i])).scaleb(-self.dtype.scale))
            elif self.dtype.is_boolean:
                out.append(bool(vals[i]))
            elif self.dtype.is_floating:
                out.append(float(vals[i]))
            else:
                out.append(int(vals[i]))
        return out

    def to_arrow(self):
        import pyarrow as pa
        at = dts.to_arrow_type(self.dtype)
        if self.dtype.is_string or self.dtype.is_array:
            return pa.array(self.to_pylist(), type=at)
        vals = self.to_numpy()
        valid = self.validity_numpy()
        if self.dtype.is_timestamp:
            vals = vals.astype("datetime64[us]")
        elif self.dtype.is_date:
            vals = vals.astype("datetime64[D]")
        elif self.dtype.is_decimal:
            return pa.array(self.to_pylist(), type=at)
        mask = None if valid.all() else ~valid
        return pa.array(vals, type=at, mask=mask)

    # ------------------------------------------------------------------- misc --
    def with_nrows(self, nrows: int) -> "Column":
        # slot copy so the clone keeps BOTH the exact host buffer and
        # any already-materialized device copy (re-upload-free slicing)
        c = Column.__new__(Column)
        c.dtype = self.dtype
        c._np_data = self._np_data
        c._jax_data = self._jax_data
        c._np_validity = self._np_validity
        c._jax_validity = self._jax_validity
        c._np_offsets = self._np_offsets
        c._jax_offsets = self._jax_offsets
        c.dictionary = self.dictionary
        c._row_count = RowCount.wrap(nrows)
        return c

    def __repr__(self) -> str:
        return (f"Column({self.dtype}, nrows={self.nrows}, "
                f"capacity={self.capacity}, nulls={self.has_nulls})")
