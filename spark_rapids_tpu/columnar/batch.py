"""ColumnarBatch: a set of equal-length device columns.

Counterpart of Spark's ``ColumnarBatch`` of GpuColumnVectors flowing between
GpuExecs (SURVEY.md section 1 "data-plane containment").  All columns share one
logical ``nrows`` and one row capacity; batches flow device-resident between
TPU operators, and crossing back to the host happens only at explicit
collect/transition points (exec/collect.py).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.column import (
    Column, RowCount, bucket_capacity)
from spark_rapids_tpu.columnar.dtypes import DataType

Schema = Sequence[Tuple[str, DataType]]


class ColumnarBatch:
    # __weakref__: the serving result cache (serving/reuse.py) tracks
    # in-memory input batches weakly — id()-based fingerprints are only
    # sound while the referent lives, and the cache must never pin a
    # client's batches
    __slots__ = ("columns", "_row_count", "transient_wire_bytes",
                 "__weakref__")

    def __init__(self, columns: Dict[str, Column], nrows=None):
        self.columns: Dict[str, Column] = dict(columns)
        # transient headroom a shuffle-received batch still pins in HBM
        # beyond its own columns: the packed exchange's lane payloads
        # live until the next program launch reuses their buffers, so
        # spill registration (memory/spill.py) counts this against the
        # DEVICE budget while the batch is device-resident.  Consumed
        # once — the first downstream materialization (pipeline /
        # coalesce) zeroes it.
        self.transient_wire_bytes: int = 0
        if nrows is None:
            if not columns:
                raise ValueError("empty batch needs explicit nrows")
            nrows = next(iter(columns.values())).row_count
        self._row_count = RowCount.wrap(nrows)
        if self._row_count.is_concrete:
            # deferred counts skip the cross-column check: forcing each
            # column's device scalar here would defeat the deferral (the
            # count is shared from one kernel output anyway)
            n = int(self._row_count)
            for name, col in self.columns.items():
                if col.row_count.is_concrete and col.nrows != n:
                    raise ValueError(
                        f"column {name} nrows {col.nrows} != batch {n}")

    @property
    def nrows(self) -> int:
        """Concrete row count (syncs once if carried lazily on device)."""
        return int(self._row_count)

    @property
    def row_count(self) -> RowCount:
        """The possibly-lazy count; device paths use
        ``row_count.device_i32()`` instead of ``nrows`` so a deferred
        aggregate count never forces a host sync."""
        return self._row_count

    # ------------------------------------------------------------------ basics --
    @property
    def names(self) -> List[str]:
        return list(self.columns)

    @property
    def schema(self) -> List[Tuple[str, DataType]]:
        return [(n, c.dtype) for n, c in self.columns.items()]

    @property
    def capacity(self) -> int:
        if not self.columns:
            return bucket_capacity(self.nrows)
        return next(iter(self.columns.values())).capacity

    def column(self, name: str) -> Column:
        return self.columns[name]

    def device_size_bytes(self) -> int:
        return sum(c.device_size_bytes() for c in self.columns.values())

    def __len__(self) -> int:
        return self.nrows

    def __repr__(self) -> str:
        cols = ", ".join(f"{n}:{c.dtype}" for n, c in self.columns.items())
        return f"ColumnarBatch[{self.nrows} rows]({cols})"

    # ------------------------------------------------------------ host interop --
    @classmethod
    def from_pydict(cls, data: Dict[str, Sequence],
                    capacity: Optional[int] = None) -> "ColumnarBatch":
        nrows = len(next(iter(data.values()))) if data else 0
        cap = capacity or bucket_capacity(nrows)
        cols = {}
        for name, values in data.items():
            if isinstance(values, Column):
                cols[name] = values
                continue
            arr = np.asarray(values) if not isinstance(values, (list, tuple)) \
                else values
            if isinstance(arr, (list, tuple)):
                if any(isinstance(v, str) or v is None for v in arr) and \
                        any(isinstance(v, str) for v in arr):
                    cols[name] = Column.from_strings(arr, capacity=cap)
                    continue
                if any(isinstance(v, (list, tuple, np.ndarray))
                       for v in arr):
                    flat = [e for v in arr if v is not None for e in v]
                    edt = dts.from_numpy_dtype(np.asarray(
                        flat if flat else [0]).dtype)
                    cols[name] = Column.from_arrays(arr, edt, capacity=cap)
                    continue
                validity = np.array([v is not None for v in arr])
                filled = [0 if v is None else v for v in arr]
                present = [v for v in arr if v is not None]
                if present and all(isinstance(v, bool) for v in present):
                    # bools + None otherwise infer as int64
                    filled = np.array([bool(v) for v in filled],
                                      dtype=np.bool_)
                cols[name] = Column.from_numpy(
                    np.asarray(filled), capacity=cap,
                    validity=None if validity.all() else validity)
            else:
                cols[name] = Column.from_numpy(arr, capacity=cap)
        return cls(cols, nrows)

    @classmethod
    def from_arrow(cls, table, capacity: Optional[int] = None) -> "ColumnarBatch":
        from spark_rapids_tpu.columnar import nested
        if nested.has_nested(table):
            table = nested.shred_table(table)
        nrows = table.num_rows
        cap = capacity or bucket_capacity(nrows)
        cols = {name: Column.from_arrow(table.column(name), capacity=cap)
                for name in table.column_names}
        return cls(cols, nrows)

    @classmethod
    def from_pandas(cls, df, capacity: Optional[int] = None) -> "ColumnarBatch":
        import pyarrow as pa
        return cls.from_arrow(pa.Table.from_pandas(df, preserve_index=False),
                              capacity=capacity)

    def to_arrow(self):
        import jax
        import pyarrow as pa
        # Host-built columns export their EXACT numpy buffers and never
        # touch the device (the .data property would materialize a
        # device copy — on emulated-f64 TPUs the round trip perturbs
        # doubles, see Column's docstring).  For genuinely
        # device-resident buffers, gather everything in ONE device_get:
        # per-buffer np.asarray would pay a full round trip each
        # (dominant with a remote-tunnel device).
        def devbuf(c, kind):
            if getattr(c, f"_np_{kind}") is not None:
                return None
            return getattr(c, f"_jax_{kind}")

        device_bufs = []
        seen = set()
        for c in self.columns.values():
            for kind in ("data", "validity", "offsets"):
                buf = devbuf(c, kind)
                if buf is not None and id(buf) not in seen:
                    seen.add(id(buf))
                    device_bufs.append(buf)
        if device_bufs:
            from spark_rapids_tpu.utils import hostsync
            fetched = hostsync.fetch_all(device_bufs)
            cache = {id(d): h for d, h in zip(device_bufs, fetched)}

            def pick(c, kind):
                np_buf = getattr(c, f"_np_{kind}")
                if np_buf is not None:
                    return np_buf
                jb = getattr(c, f"_jax_{kind}")
                return cache.get(id(jb), jb) if jb is not None else None

            cols = {}
            for n, c in self.columns.items():
                cols[n] = Column(
                    c.dtype, pick(c, "data"), c.nrows,
                    validity=pick(c, "validity"),
                    offsets=pick(c, "offsets"),
                    dictionary=c.dictionary)
            return pa.table({n: c.to_arrow() for n, c in cols.items()})
        return pa.table({n: c.to_arrow() for n, c in self.columns.items()})

    def to_pandas(self):
        return self.to_arrow().to_pandas()

    def to_pydict(self):
        return {n: c.to_pylist() for n, c in self.columns.items()}

    # --------------------------------------------------------------- reshaping --
    def select(self, names: Iterable[str]) -> "ColumnarBatch":
        return ColumnarBatch({n: self.columns[n] for n in names},
                             self._row_count)

    def rename(self, mapping: Dict[str, str]) -> "ColumnarBatch":
        return ColumnarBatch({mapping.get(n, n): c
                              for n, c in self.columns.items()},
                             self._row_count)

    def with_column(self, name: str, col: Column) -> "ColumnarBatch":
        cols = dict(self.columns)
        cols[name] = col
        return ColumnarBatch(cols, self._row_count)


def empty_batch(schema: Schema, capacity: int = 0) -> ColumnarBatch:
    cap = bucket_capacity(max(capacity, 1))
    cols = {}
    for name, dt in schema:
        if dt.is_array:
            cols[name] = Column.from_arrays([], dt.element, capacity=cap)
        elif dt.is_string:
            cols[name] = Column.from_strings([], capacity=cap)
        else:
            cols[name] = Column.from_numpy(
                np.zeros(0, dtype=dt.storage), dtype=dt, capacity=cap)
    return ColumnarBatch(cols, 0)
