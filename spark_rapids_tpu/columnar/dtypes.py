"""Logical data types for TPU columnar batches.

Counterpart of the Spark<->cudf DType mapping in the reference
(``GpuColumnVector.java:46`` `getNonNestedRapidsType`), re-designed for XLA:
every logical type maps onto a *storage* dtype that XLA handles natively on
TPU.  Notable departures from the cudf mapping:

* STRING is not a single device buffer-pair type; the Column stores UTF-8
  bytes + int32 offsets as two fixed-capacity arrays (see ``strings.py``).
* DECIMAL follows the reference's DECIMAL_64 restriction (precision <= 18,
  ``TypeSig.DECIMAL_64`` in TypeChecks.scala): unscaled int64 storage.
* TIMESTAMP is int64 microseconds, UTC only — the reference refuses
  non-UTC sessions (SURVEY.md Appendix B), we inherit that contract.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class DataType:
    """A logical column type.

    ``name``     logical name, e.g. ``int`` / ``string`` / ``decimal(10,2)``
    ``storage``  numpy dtype used for the device representation (strings use
                 uint8 chars + int32 offsets and set storage to object-free
                 ``np.uint8`` for the char buffer).
    """

    name: str
    storage: Any  # np.dtype-like
    # decimal only
    precision: Optional[int] = None
    scale: Optional[int] = None
    # array only: the element type (storage then holds the ELEMENT storage
    # dtype — an array column is flat element values + int32 row offsets,
    # the same layout strings use for their chars)
    element: Optional["DataType"] = None
    # struct only: ((field_name, DataType), ...); map uses exactly two
    # pseudo-fields ("key", K) and ("value", V).  Nested types never
    # materialize as device containers — they are SHREDDED into flat
    # physical columns (struct field "a" of column "s" lives as column
    # "s.a"; a map "m" as two aligned array columns "m.__key" /
    # "m.__value") and reassembled only at the Arrow output boundary.
    # The dot and the __key/__value suffixes are reserved naming.
    fields: Optional[tuple] = None

    # ---- classification helpers -------------------------------------------------
    @property
    def is_string(self) -> bool:
        return self.name == "string"

    @property
    def is_array(self) -> bool:
        return self.element is not None

    @property
    def is_struct(self) -> bool:
        return self.fields is not None and not self.name.startswith("map<")

    @property
    def is_map(self) -> bool:
        return self.fields is not None and self.name.startswith("map<")

    @property
    def is_nested(self) -> bool:
        return self.fields is not None

    @property
    def key_type(self) -> "DataType":
        assert self.is_map
        return self.fields[0][1]

    @property
    def value_type(self) -> "DataType":
        assert self.is_map
        return self.fields[1][1]

    @property
    def has_offsets(self) -> bool:
        """True when the device layout is (flat values, int32 offsets):
        strings (chars) and arrays (elements)."""
        return self.is_string or self.is_array

    @property
    def is_boolean(self) -> bool:
        return self.name == "boolean"

    @property
    def is_integral(self) -> bool:
        return self.name in ("tinyint", "smallint", "int", "bigint")

    @property
    def is_floating(self) -> bool:
        return self.name in ("float", "double")

    @property
    def is_numeric(self) -> bool:
        return self.is_integral or self.is_floating or self.is_decimal

    @property
    def is_decimal(self) -> bool:
        return self.name.startswith("decimal")

    @property
    def is_date(self) -> bool:
        return self.name == "date"

    @property
    def is_timestamp(self) -> bool:
        return self.name == "timestamp"

    @property
    def is_datetime(self) -> bool:
        return self.is_date or self.is_timestamp

    def __repr__(self) -> str:
        return self.name

    def __str__(self) -> str:
        return self.name


BOOL = DataType("boolean", np.dtype(np.bool_))
INT8 = DataType("tinyint", np.dtype(np.int8))
INT16 = DataType("smallint", np.dtype(np.int16))
INT32 = DataType("int", np.dtype(np.int32))
INT64 = DataType("bigint", np.dtype(np.int64))
FLOAT32 = DataType("float", np.dtype(np.float32))
FLOAT64 = DataType("double", np.dtype(np.float64))
# chars buffer storage; offsets are always int32 (2^31 byte cap per batch —
# the same per-column row/byte limit the reference designs around, see
# SURVEY.md Appendix B "2 GiB hard cap").
STRING = DataType("string", np.dtype(np.uint8))
DATE32 = DataType("date", np.dtype(np.int32))  # days since unix epoch
TIMESTAMP_US = DataType("timestamp", np.dtype(np.int64))  # micros since epoch, UTC


def ArrayType(element: DataType) -> DataType:
    """ARRAY<element>: flat element buffer + int32 offsets (the reference
    keeps nested types in cudf list columns, GpuColumnVector.java; here the
    layout mirrors the string chars+offsets pair so all offset-aware
    kernels — gather, concat, serialize — apply unchanged)."""
    if element.has_offsets:
        raise ValueError(
            f"nested element type {element} not supported (single-level "
            "arrays of fixed-width elements only)")
    return DataType(f"array<{element.name}>", element.storage,
                    element=element)


def StructType(fields) -> DataType:
    """STRUCT<f1: t1, ...> — a logical grouping over shredded flat columns
    (see the ``fields`` attribute note above; GpuColumnVector keeps these
    as cudf struct children, here each field is an ordinary flat column,
    which is the layout XLA wants anyway)."""
    fields = tuple((str(n), t) for n, t in fields)
    if not fields:
        raise ValueError("struct needs at least one field")
    inner = ",".join(f"{n}:{t.name}" for n, t in fields)
    return DataType(f"struct<{inner}>", np.dtype(np.uint8), fields=fields)


def MapType(key: DataType, value: DataType) -> DataType:
    """MAP<K, V> — shredded to two aligned array columns (same per-row
    offsets): ``<name>.__key`` of ARRAY<K> and ``<name>.__value`` of
    ARRAY<V>."""
    if key.has_offsets or value.has_offsets or key.is_nested \
            or value.is_nested:
        raise ValueError(
            f"map<{key},{value}> unsupported: key/value must be "
            "fixed-width scalar types")
    return DataType(f"map<{key.name},{value.name}>", np.dtype(np.uint8),
                    fields=(("key", key), ("value", value)))


def DecimalType(precision: int, scale: int) -> DataType:
    """DECIMAL_64 only, like the reference snapshot (precision <= 18)."""
    if precision > 18:
        raise ValueError(
            f"decimal precision {precision} > 18 unsupported (DECIMAL_64 only, "
            "matching reference TypeSig.DECIMAL_64)")
    if scale < 0 or scale > precision:
        raise ValueError(f"bad decimal scale {scale} for precision {precision}")
    return DataType(f"decimal({precision},{scale})", np.dtype(np.int64),
                    precision=precision, scale=scale)


_BY_NAME = {t.name: t for t in
            (BOOL, INT8, INT16, INT32, INT64, FLOAT32, FLOAT64, STRING,
             DATE32, TIMESTAMP_US)}


def dtype_from_name(name: str) -> DataType:
    name = name.strip().lower()
    if name in _BY_NAME:
        return _BY_NAME[name]
    if name.startswith("array<") and name.endswith(">"):
        return ArrayType(dtype_from_name(name[6:-1]))
    if name.startswith("decimal"):
        inner = name[name.index("(") + 1:name.index(")")]
        p, s = (int(x) for x in inner.split(","))
        return DecimalType(p, s)
    aliases = {"long": INT64, "integer": INT32, "short": INT16, "byte": INT8,
               "bool": BOOL, "str": STRING, "float64": FLOAT64,
               "float32": FLOAT32}
    if name in aliases:
        return aliases[name]
    raise ValueError(f"unknown data type name: {name}")


def from_numpy_dtype(dt) -> DataType:
    dt = np.dtype(dt)
    mapping = {
        np.dtype(np.bool_): BOOL,
        np.dtype(np.int8): INT8,
        np.dtype(np.int16): INT16,
        np.dtype(np.int32): INT32,
        np.dtype(np.int64): INT64,
        np.dtype(np.float32): FLOAT32,
        np.dtype(np.float64): FLOAT64,
    }
    if dt in mapping:
        return mapping[dt]
    if dt.kind == "M":  # datetime64
        return TIMESTAMP_US
    if dt.kind in ("U", "S", "O"):
        return STRING
    raise ValueError(f"unsupported numpy dtype {dt}")


def from_arrow_type(at) -> DataType:
    import pyarrow as pa
    if pa.types.is_struct(at):
        return StructType((f.name, from_arrow_type(f.type)) for f in at)
    if pa.types.is_map(at):
        return MapType(from_arrow_type(at.key_type),
                       from_arrow_type(at.item_type))
    if pa.types.is_list(at) or pa.types.is_large_list(at):
        return ArrayType(from_arrow_type(at.value_type))
    if pa.types.is_boolean(at):
        return BOOL
    if pa.types.is_int8(at):
        return INT8
    if pa.types.is_int16(at):
        return INT16
    if pa.types.is_int32(at):
        return INT32
    if pa.types.is_int64(at):
        return INT64
    if pa.types.is_float32(at):
        return FLOAT32
    if pa.types.is_float64(at):
        return FLOAT64
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return STRING
    if pa.types.is_date32(at):
        return DATE32
    if pa.types.is_timestamp(at):
        return TIMESTAMP_US
    if pa.types.is_decimal(at):
        return DecimalType(at.precision, at.scale)
    if pa.types.is_dictionary(at):
        return from_arrow_type(at.value_type)
    raise ValueError(f"unsupported arrow type {at}")


def to_arrow_type(dt: DataType):
    import pyarrow as pa
    if dt is BOOL or dt.name == "boolean":
        return pa.bool_()
    if dt.name == "tinyint":
        return pa.int8()
    if dt.name == "smallint":
        return pa.int16()
    if dt.name == "int":
        return pa.int32()
    if dt.name == "bigint":
        return pa.int64()
    if dt.name == "float":
        return pa.float32()
    if dt.name == "double":
        return pa.float64()
    if dt.is_string:
        return pa.string()
    if dt.is_array:
        return pa.list_(to_arrow_type(dt.element))
    if dt.is_date:
        return pa.date32()
    if dt.is_timestamp:
        return pa.timestamp("us", tz="UTC")
    if dt.is_decimal:
        return pa.decimal128(dt.precision, dt.scale)
    if dt.is_map:
        return pa.map_(to_arrow_type(dt.key_type),
                       to_arrow_type(dt.value_type))
    if dt.is_struct:
        return pa.struct([pa.field(n, to_arrow_type(t))
                          for n, t in dt.fields])
    raise ValueError(f"no arrow type for {dt}")
