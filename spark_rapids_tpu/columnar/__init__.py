from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.columnar.batch import ColumnarBatch

__all__ = ["DataType", "Column", "ColumnarBatch"]
