"""Nested-type shredding: struct/map columns <-> flat physical columns.

The reference carries nested data through execution as cudf struct/list
device columns (``GpuColumnVector.java``, ``GpuGenerateExec.scala``).  On
TPU a container column is the wrong shape for XLA — so nested types are
SHREDDED at ingest into ordinary flat columns (the Dremel/columnar-shredding
representation) and reassembled only at the Arrow output boundary:

* ``STRUCT`` column ``s`` with fields ``a``, ``b``  ->  flat columns
  ``s.a``, ``s.b`` (recursively: ``s.a.c`` for nested structs).  Struct
  nulls propagate into the children at shred time (a null struct row has
  all-null fields), matching how field access on a null struct behaves.
* ``MAP`` column ``m``  ->  two aligned array columns ``m.__key`` /
  ``m.__value`` sharing per-row offsets.

Everything downstream — gather, filter compaction, joins, sort, spill,
serialization — operates on the flat columns with zero nested-awareness,
which is the point: one code path, fully XLA-native.  The dot and the
``__key``/``__value`` suffixes are reserved column naming.
"""

from __future__ import annotations

from typing import List, Tuple

MAP_KEY_SUFFIX = ".__key"
MAP_VALUE_SUFFIX = ".__value"


def check_reserved_names(names) -> None:
    """Ingest-boundary guard: user column names must not collide with
    the shredding convention, or assemble_table would silently reshape
    them into structs/maps on output."""
    bad = [n for n in names if "." in n]
    if bad:
        raise ValueError(
            f"column name(s) {bad} contain '.', which is reserved for "
            "nested-type shredding; rename the column(s)")


def is_shredded_map(name: str, schema_names) -> bool:
    """True when a bare column reference names a shredded MAP column:
    absent itself, both halves present.  The single definition every
    bind-time dispatch site uses."""
    return (name not in schema_names
            and name + MAP_KEY_SUFFIX in schema_names
            and name + MAP_VALUE_SUFFIX in schema_names)


def has_nested(table) -> bool:
    import pyarrow as pa
    return any(pa.types.is_struct(f.type) or pa.types.is_map(f.type)
               for f in table.schema)


def _shred_array(name: str, arr) -> List[Tuple[str, object]]:
    """One (possibly nested) arrow column -> [(flat_name, arrow_array)]."""
    import pyarrow as pa
    import pyarrow.compute as pc
    arr = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    t = arr.type
    if pa.types.is_struct(t):
        out = []
        null_mask = pc.is_null(arr) if arr.null_count else None
        for f in t:
            child = arr.field(f.name)
            if null_mask is not None:
                # a null struct row reads as null in every field
                child = pc.if_else(null_mask, pa.nulls(len(arr), f.type),
                                   child)
            out.extend(_shred_array(f"{name}.{f.name}", child))
        return out
    if pa.types.is_map(t):
        from spark_rapids_tpu.columnar.dtypes import from_arrow_type
        from_arrow_type(t)  # raises the clear fixed-width-only error
        if arr.null_count:
            raise ValueError(
                f"map column {name!r}: null map rows unsupported "
                "(use an empty map)")
        offsets = arr.offsets
        keys = pa.ListArray.from_arrays(offsets, arr.keys)
        items = pa.ListArray.from_arrays(offsets, arr.items)
        return [(name + MAP_KEY_SUFFIX, keys),
                (name + MAP_VALUE_SUFFIX, items)]
    return [(name, arr)]


def shred_table(table):
    """Flatten every struct/map column of an arrow table (no-op copy
    of already-flat columns)."""
    import pyarrow as pa
    cols, names = [], []
    for fname in table.column_names:
        for n, a in _shred_array(fname, table.column(fname)):
            names.append(n)
            cols.append(a)
    return pa.table(dict(zip(names, cols)))


# ------------------------------------------------------------------ assembly --
def _group_prefixes(names: List[str]):
    """Group flat names into output slots, preserving first-seen order.

    Returns [(out_name, kind, members)] where kind is 'plain' | 'map' |
    'struct'; members lists the flat column names consumed."""
    slots = []
    consumed = set()
    for n in names:
        if n in consumed:
            continue
        if n.endswith(MAP_KEY_SUFFIX) or n.endswith(MAP_VALUE_SUFFIX):
            suffix = MAP_KEY_SUFFIX if n.endswith(MAP_KEY_SUFFIX) \
                else MAP_VALUE_SUFFIX
            base = n[:-len(suffix)]
            if "." not in base:
                # a complete TOP-LEVEL key/value pair assembles to a map
                # regardless of projection order; an orphan half (e.g. a
                # lone map_keys() output) stays a plain list column.  A
                # dotted base (s.m.__key) is a map INSIDE a struct — it
                # falls through to struct grouping and reassembles during
                # the recursive struct pass.
                k, v = base + MAP_KEY_SUFFIX, base + MAP_VALUE_SUFFIX
                if k in names and v in names and k not in consumed \
                        and v not in consumed:
                    slots.append((base, "map", [k, v]))
                    consumed.update((k, v))
                else:
                    slots.append((n, "plain", [n]))
                    consumed.add(n)
                continue
        if "." in n:
            base = n.split(".", 1)[0]
            if base in names:
                raise ValueError(
                    f"ambiguous output: both column {base!r} and struct "
                    f"member {n!r} present — alias one of them")
            members = [m for m in names if m not in consumed and
                       m.startswith(base + ".")]
            slots.append((base, "struct", members))
            consumed.update(members)
            continue
        slots.append((n, "plain", [n]))
        consumed.add(n)
    return slots


def _assemble_struct(prefix: str, members: List[Tuple[str, object]]):
    """members: [(name_relative_to_prefix, array)] -> StructArray."""
    import pyarrow as pa
    groups = _group_prefixes([n for n, _ in members])
    by_name = dict(members)
    fields, arrays = [], []
    for out_name, kind, flat in groups:
        if kind == "map":
            arr = _assemble_map(by_name[flat[0]], by_name[flat[1]])
        elif kind == "struct":
            arr = _assemble_struct(
                out_name,
                [(n[len(out_name) + 1:], by_name[n]) for n in flat])
        else:
            arr = by_name[flat[0]]
        fields.append(pa.field(out_name, arr.type))
        arrays.append(arr)
    return pa.StructArray.from_arrays(arrays, fields=fields)


def _assemble_map(keys_list, values_list):
    import pyarrow as pa
    keys_list = keys_list.combine_chunks() \
        if isinstance(keys_list, pa.ChunkedArray) else keys_list
    values_list = values_list.combine_chunks() \
        if isinstance(values_list, pa.ChunkedArray) else values_list
    return pa.MapArray.from_arrays(keys_list.offsets, keys_list.values,
                                   values_list.values)


def assemble_table(table):
    """Inverse of shred_table, driven purely by the naming convention.
    Tables without reserved names pass through untouched."""
    import pyarrow as pa
    names = table.column_names
    if not any("." in n for n in names):
        return table
    out_names, out_cols = [], []
    for out_name, kind, flat in _group_prefixes(names):
        if kind == "map":
            col = _assemble_map(table.column(flat[0]).combine_chunks(),
                                table.column(flat[1]).combine_chunks())
        elif kind == "struct":
            col = _assemble_struct(
                out_name,
                [(n[len(out_name) + 1:],
                  table.column(n).combine_chunks()) for n in flat])
        else:
            col = table.column(flat[0])
        out_names.append(out_name)
        out_cols.append(col)
    return pa.table(dict(zip(out_names, out_cols)))
