"""Type support signatures.

Lightweight analog of the reference's ``TypeChecks.scala`` TypeSig algebra
(2,060 LoC): each replacement rule declares which input/output types it
supports on TPU; the planner tags nodes that fall outside as
"will not work on TPU" with a reason, and generates the supported-ops doc.
"""

from __future__ import annotations

from typing import Iterable, Optional, Set

from spark_rapids_tpu.columnar.dtypes import DataType


class TypeSig:
    """A set of supported logical type names (+ decimal/array flags)."""

    def __init__(self, names: Iterable[str], decimal: bool = False,
                 arrays: bool = False):
        self.names: Set[str] = set(names)
        self.decimal = decimal
        self.arrays = arrays

    def __add__(self, other: "TypeSig") -> "TypeSig":
        return TypeSig(self.names | other.names,
                       self.decimal or other.decimal,
                       self.arrays or other.arrays)

    def supports(self, dt: DataType) -> bool:
        if dt.is_decimal:
            return self.decimal
        if dt.is_array:
            return self.arrays and dt.element is not None and \
                not dt.element.has_offsets
        return dt.name in self.names

    def reason_if_unsupported(self, dt: DataType,
                              what: str) -> Optional[str]:
        if self.supports(dt):
            return None
        return f"{what} has unsupported type {dt}"

    def __repr__(self):
        names = sorted(self.names) + (["decimal"] if self.decimal else [])
        return "TypeSig(" + ", ".join(names) + ")"


BOOLEAN = TypeSig(["boolean"])
INTEGRAL = TypeSig(["tinyint", "smallint", "int", "bigint"])
FP = TypeSig(["float", "double"])
DECIMAL_64 = TypeSig([], decimal=True)
NUMERIC = INTEGRAL + FP + DECIMAL_64
STRING = TypeSig(["string"])
DATETIME = TypeSig(["date", "timestamp"])
# the common cudf-equivalent set (TypeChecks.scala:557 commonCudfTypes)
COMMON = BOOLEAN + NUMERIC + STRING + DATETIME
ORDERABLE = COMMON
# single-level arrays of fixed-width elements (TypeSig.ARRAY analog,
# TypeChecks.scala nested support)
ARRAY = TypeSig([], arrays=True)
ALL = COMMON + ARRAY
