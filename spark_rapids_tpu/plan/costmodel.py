"""Self-tuning cost-based planner: ONE evidence-fed cost model over
every tuning knob, with mid-query adaptive re-planning.

The engine accumulated a dozen independently tuned heuristics — slot
EMA + speculation, ragged ``minSavings``, ``hostStaging.thresholdBytes``,
topology strategy, fusion ``maxChainOps``, encoding knobs, coalesce
goals — each locally tuned, none sharing evidence.  This module unifies
them behind one decision authority:

* **Evidence** comes from the PR11 :class:`ObservationStore` — per-site
  ``{rows, bytes, skew, compile_ms, span_ms}`` keyed by the SAME
  structural site ids the jit cache and checkpoint lineage use,
  persisted beside the AOT cache dir, so a warm start has warm *plans*,
  not just warm executables.  The model's own records use a ``cm:``
  sid prefix (and readable ``op:<Name>`` records for per-operator
  weights) so they coexist with the tracing runtime's records in one
  JSONL file; a site with no history falls back to the built-in
  tables below ("GPU-Augmented OLAP Execution Engine" is the exemplar
  for cost-modeled offload decisions, Theseus for movement costs).
* **Decisions** — exchange strategy (uniform vs ragged vs gather vs
  host-staged), the staging threshold, fusion chain boundaries,
  coded-vs-decoded execution, shuffle slot priors, and the coalesce
  goal — are each served by one API here, consumed by the SlotPlanner,
  ``plan/overrides``, ``DistributedAggregate``/``DistributedHashJoin``
  and the planner-inserted coalesce.  The hand-tuned conf keys stay as
  *overrides*: an explicitly-set key wins and the model only decides
  knobs the user left unset (``RapidsConf.is_set``).
* **Ledger** — every decision records (knob, site, chosen,
  alternatives with predicted costs, override/evidence provenance)
  into a per-query ledger that rides the QueryEnd ``planner`` dict →
  eventlog ``QueryInfo.planner`` → the profiling "Planner decisions"
  section with a mispredict health check; observed costs fold back
  into the ledger AND the observation store, so the model converges.
* **Mid-query adaptive re-planning** — when a launch's measured
  statistics contradict the plan-time decision past the hysteresis
  band (measured skew says ragged, the plan chose uniform), the model
  folds the fresh evidence and raises a RETRYABLE
  :class:`ReplanRequested`: a non-failure entry point into the
  recovery ladder's re-drive.  The retry rung keeps the mesh layout,
  so completed stages splice from the stage-checkpoint lineage and
  only the contradicted subtree re-plans — at most ONCE per query.

Default-off (``spark.rapids.tpu.costModel.enabled``): with the knob
off no model exists, every consumption site is a single None check,
and plans/events/results are bit-identical to HEAD.  A corrupt or
truncated evidence file — or a ledger/persistence write fault —
degrades the model to its built-in defaults with a ``CostModelInvalid``
event (the ``costmodel.load`` injection point), never a failed or
wrong query.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from spark_rapids_tpu.robustness.inject import register_point
from spark_rapids_tpu.utils.tracing import ObservationStore, site_id

# chaos surface: raise/delay rules degrade evidence load (and the
# QueryEnd ledger/evidence persistence) to built-in defaults; corrupt
# rules bit-flip the raw observation bytes before parsing — either way
# a CostModelInvalid event, never a failed or wrong query
register_point("costmodel.load")

# ---------------------------------------------------------------------------
# Built-in cost tables (relative units — only ratios matter).  These are
# the cold-start fallback when a site has no observation history; the
# docs/performance.md "Self-tuning planner" decision table documents
# which formula each knob uses.
W_ICI_BYTE = 1.0        # device collective, per padded wire byte
W_DCN_BYTE = 8.0        # cross-host (DCN) collective byte
W_STAGED_BYTE = 6.0     # host staging per useful byte (D2H + codec + H2D)
W_STAGED_FIXED = float(1 << 20)  # host round-trip setup, in
#                                  bytes-equivalents: staging never wins
#                                  on tiny payloads
W_PERMUTE_ROUND = 4096.0  # per extra collective-permute round (launch
#                           latency, amortized in bytes-equivalents)
RAGGED_WIRE_OVERHEAD = 1.25  # ragged payload vs perfectly dense
RAGGED_ROUNDS_EST = 2.0      # typical surplus rounds for a skewed site

# plan-time priors
RAGGED_MODEL_MIN_SAVINGS = 1.2  # launch-time minSavings when the model
#                                 (not the conf) governs plan_ragged
STAGING_BUDGET_FRACTION = 0.5   # of the spill catalog's device budget:
#                                 padded payloads past this predict staged
COALESCE_BUDGET_DIVISOR = 4     # coalesce goal <= device budget / this
COALESCE_GOAL_FLOOR = 1 << 16
COMPILE_HEAVY_MS = 10_000.0     # observed worst compile past this
#                                 halves the fusion chain bound
MISPREDICT_FACTOR = 4.0         # observed >= this x predicted = mispredict

# built-in prior for coded-vs-decoded execution: the PR10 string-q1 A/B
# measured the encoded fused stage ~1.8x the decoded path
ENCODED_SPEEDUP_PRIOR = 1.8

# exec node -> CBO operator-kind mapping for per-op observed weights
_OP_NAMES = {
    "TpuProjectExec": "Project", "TpuFilterExec": "Filter",
    "TpuHashAggregateExec": "Aggregate", "TpuHashJoinExec": "Join",
    "TpuSortExec": "Sort", "TpuTopNExec": "Sort",
    "TpuWindowExec": "Window", "TpuGenerateExec": "Generate",
    "TpuLocalLimitExec": "Limit", "TpuUnionExec": "Union",
}


@dataclass
class ExchangePlan:
    """One plan-time exchange decision for a consumer site.

    ``mode`` is the predicted-cheapest strategy; ``ragged`` arms the
    consumer's ragged capability (histograms become mandatory — the
    site never launches speculatively); ``staging_thr`` is the
    effective host-staging threshold in bytes (None = defer to the
    conf helper ``exchange_async.staging_threshold``, i.e. the user
    explicitly set the knob)."""

    mode: str                      # uniform | ragged | gather | staged
    ragged: bool
    min_savings: float
    staging_thr: Optional[int]


class _MemoryStore(ObservationStore):
    """Evidence store when no directory resolves: same EMA semantics,
    in-memory only — decisions still converge within the process,
    nothing persists across it."""

    def __init__(self):  # noqa: D401 - deliberate no-super
        self.dir = None
        self.path = None
        self._lock = threading.Lock()
        self.records: Dict[str, Dict[str, float]] = {}
        self._dirty = False
        self._dirty_sids: set = set()

    def flush(self) -> None:
        pass


class CostModel:
    """One per session (``session.cost_model``; None when the knob is
    off — every consumption site pays a single getattr)."""

    def __init__(self, session, conf):
        from spark_rapids_tpu.config import rapids_conf as rc
        self.session = session
        self.conf = conf
        self.hysteresis = conf.get(rc.COSTMODEL_REPLAN_HYSTERESIS)
        self.replan_conf = conf.get(rc.COSTMODEL_REPLAN_ENABLED) and \
            conf.get(rc.QUERY_RECOVERY_ENABLED)
        self.dir = (conf.get(rc.COSTMODEL_DIR)
                    or conf.get(rc.JIT_CACHE_DIR)
                    or conf.get(rc.TRACE_DIR) or None)
        self.invalid_loads = 0
        self._invalid_reported = 0  # drained into per-query deltas
        self.replan_count = 0
        # cached worst-compile scan (value, computed_at): the full
        # store walk must not run per plan on a store that can hold
        # thousands of multi-session site records
        self._compile_worst = (0.0, float("-inf"))
        self._lock = threading.Lock()
        # per-query decision ledger, keyed by effective thread ident
        # (the PR6 attribution discipline: concurrent queries must not
        # smear each other's decisions); popped at every QueryEnd
        self._ledger: Dict[int, List[dict]] = {}
        self._ledger_keys: Dict[int, set] = {}
        self.evidence: Dict[str, Dict[str, float]] = {}
        self.store: ObservationStore = _MemoryStore()
        self._open_store()

    # ------------------------------------------------------- evidence --
    def _invalid(self, reason: str) -> None:
        self.invalid_loads += 1
        try:
            from spark_rapids_tpu.utils.events import emit_on_session
            emit_on_session("CostModelInvalid", session=self.session,
                            reason=reason)
        except Exception:
            pass  # the degrade record must never fail a query

    def _open_store(self) -> None:
        """Load persisted evidence (guarded by ``costmodel.load``) and
        attach the write-side store: the process-global tracing store
        when it already persists to the same directory (one store, one
        flush discipline), else the model's own."""
        self.evidence = self._load_evidence()
        if not self.dir:
            return
        try:
            from spark_rapids_tpu.utils import tracing
            shared = tracing.observation_store()
            if shared is not None and \
                    getattr(shared, "dir", None) == self.dir:
                self.store = shared
                return
            store = ObservationStore(self.dir)
            # the validated load is authoritative: the store's own
            # silent-skip read must not resurrect corrupt-file state
            store.records = {k: dict(v)
                             for k, v in self.evidence.items()}
            self.store = store
        except Exception as e:
            self._invalid(f"store-open: {type(e).__name__}: {e}")
            self.store = _MemoryStore()

    def _load_evidence(self) -> Dict[str, Dict[str, float]]:
        """The one guarded evidence read: raise/delay chaos rules and
        ANY parse/IO failure degrade to the built-in defaults (empty
        evidence) with a CostModelInvalid event; corrupt rules mutate
        the raw bytes before parsing, exercising the same path real
        bit rot would."""
        from spark_rapids_tpu.robustness.inject import fire, fire_mutate
        from spark_rapids_tpu.utils.tracing import OBS_FILE
        records: Dict[str, Dict[str, float]] = {}
        try:
            fire("costmodel.load")
            path = os.path.join(self.dir, OBS_FILE) if self.dir else None
            if not path or not os.path.exists(path):
                return records
            with open(path, "rb") as f:
                raw = f.read()
            raw = fire_mutate("costmodel.load", raw)
            bad = 0
            for line in raw.decode("utf-8",
                                   errors="replace").splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                    sid = rec.pop("site")
                    records[str(sid)] = {
                        k: v for k, v in rec.items()
                        if isinstance(v, (int, float))}
                except Exception:
                    bad += 1
            if bad:
                raise ValueError(f"{bad} corrupt observation line(s)")
            return records
        except Exception as e:
            self._invalid(f"load: {type(e).__name__}: {e}")
            return {}

    def evidence_for(self, site) -> Dict[str, float]:
        """Merged evidence for a structural site: the live store's
        fresh observations win over the validated persisted load;
        model (``cm:``) records win over the tracing runtime's."""
        sid = site if isinstance(site, str) else site_id(site)
        for key in (f"cm:{sid}", sid):
            rec = self.store.records.get(key)
            if rec is None:
                rec = self.evidence.get(key)
            if rec:
                return dict(rec)
        return {}

    def observe_site(self, site, **fields) -> None:
        """Fold observed per-site facts (rows/bytes/skew...) into the
        evidence store under the model's ``cm:`` namespace."""
        self._observe_sid(f"cm:{site_id(site)}", **fields)

    def _store_snapshot(self) -> Dict[str, Dict[str, float]]:
        """Shallow copy of the live store's records under its lock —
        iteration-safe while concurrent queries observe() new sites
        (a lock-free scan can die mid-iteration)."""
        lock = getattr(self.store, "_lock", None)
        if lock is None:
            return dict(self.store.records)
        with lock:
            return dict(self.store.records)

    def _observe_sid(self, sid: str, **fields) -> None:
        try:
            self.store.observe(sid, **fields)
        except Exception:
            pass  # evidence is an optimization, never a failure

    # --------------------------------------------------------- ledger --
    @staticmethod
    def _ident() -> int:
        from spark_rapids_tpu.serving import context as qc
        return qc.effective_ident()

    def _decide(self, knob: str, site, chosen: str,
                alternatives: Optional[Dict[str, float]] = None,
                override: bool = False, evidence: bool = False,
                predicted: Optional[float] = None) -> dict:
        """Record one decision in the current query's ledger (deduped
        per (knob, site) so repeated planner consultations record
        once).  Returns the (live) record so the caller can attach the
        observed cost later."""
        sid = "-" if site is None else (
            site if isinstance(site, str) else site_id(site))
        rec = {"knob": knob, "site": sid, "chosen": chosen,
               "override": bool(override), "evidence": bool(evidence)}
        if predicted is not None:
            rec["predicted"] = round(float(predicted), 3)
        if alternatives:
            rec["alternatives"] = {k: round(float(v), 3)
                                   for k, v in alternatives.items()}
        ident = self._ident()
        with self._lock:
            keys = self._ledger_keys.setdefault(ident, set())
            if (knob, sid) in keys and knob != "replan":
                for old in reversed(self._ledger.get(ident, [])):
                    if old["knob"] == knob and old["site"] == sid:
                        return old
            keys.add((knob, sid))
            self._ledger.setdefault(ident, []).append(rec)
            if len(self._ledger) > 256:
                # recycled-ident flood: drop stale entries, keep ours
                for k in list(self._ledger)[:128]:
                    if k != ident:
                        self._ledger.pop(k, None)
                        self._ledger_keys.pop(k, None)
        return rec

    def observe_outcome(self, knob: str, site,
                        observed_cost: float) -> None:
        """Attach the observed cost to the latest matching ledger
        decision — the mispredict health check's raw material."""
        sid = site if isinstance(site, str) else site_id(site)
        ident = self._ident()
        with self._lock:
            for rec in reversed(self._ledger.get(ident, [])):
                if rec["knob"] == knob and rec["site"] == sid:
                    rec["observed"] = round(float(observed_cost), 3)
                    return

    def finish_query(self) -> Dict[str, Any]:
        """The QueryEnd drain: pop this query's ledger, derive the
        mispredict/replan tallies, and persist the evidence (guarded —
        a write fault degrades with CostModelInvalid, never fails the
        query).  Returns the QueryEnd ``planner`` dict."""
        ident = self._ident()
        with self._lock:
            recs = self._ledger.pop(ident, [])
            self._ledger_keys.pop(ident, None)
            # per-query DELTA of the degraded-load counter, drained by
            # whichever envelope closes first (the process-global-delta
            # attribution discipline) — a construction-time degrade
            # must not re-stamp every later healthy query
            invalid = self.invalid_loads - self._invalid_reported
            self._invalid_reported = self.invalid_loads
        mispredicts = sum(
            1 for r in recs
            if r.get("observed") is not None and r.get("predicted")
            and r["observed"] >= MISPREDICT_FACTOR * r["predicted"])
        out = {
            "decisions": recs,
            "replans": sum(1 for r in recs if r["knob"] == "replan"
                           and r.get("applied")),
            "mispredicts": mispredicts,
            "invalidLoads": invalid,
        }
        try:
            from spark_rapids_tpu.robustness.inject import fire
            fire("costmodel.load")
            self.store.flush()
        except Exception as e:
            self._invalid(f"ledger-write: {type(e).__name__}: {e}")
        return out

    # ------------------------------------------------ exchange policy --
    def _device_budget(self) -> int:
        cat = getattr(self.session, "memory_catalog", None)
        return int(getattr(cat, "device_budget", 0) or (16 << 30))

    def _derived_staging_thr(self) -> int:
        """Budget-derived staging threshold: a padded exchange payload
        the device budget could never comfortably hold should stage
        through host RAM, not march into the spill/split rungs.  The
        query's serving memory budget tightens it further (the
        ``staging_threshold`` helper's discipline)."""
        thr = max(int(self._device_budget() *
                      STAGING_BUDGET_FRACTION), 1)
        from spark_rapids_tpu.serving import context as qc
        ctx = qc.current()
        if ctx is not None and getattr(ctx, "memory_budget", 0):
            thr = min(thr, int(ctx.memory_budget))
        return thr

    def resolve_exchange(self, site, nshards: int, op: str = "exchange",
                         strategy: str = "all_to_all") -> ExchangePlan:
        """Plan-time exchange decision for one consumer site, from
        per-site evidence (cold sites predict uniform — the built-in
        prior; ragged variants and staging estimates cost compile time
        and host work that unskewed, fitting payloads should not pay).

        Cost formulas (relative units, docs/performance.md):

        * uniform = useful_bytes * padding_factor * W_wire, with
          padding_factor = nshards^2 * observed hottest-slice skew;
        * ragged  = useful_bytes * 1.25 * W_wire + rounds * W_permute;
        * staged  = useful_bytes * W_staged (device->host + codec +
          host->device), chosen when the padded payload exceeds the
          budget-derived staging threshold;
        * gather is the topology-resolved strategy (DCN-spanning axes)
          and is recorded, not second-guessed — physics wins.

        Explicitly-set conf keys override the corresponding leg."""
        from spark_rapids_tpu.config import rapids_conf as rc
        conf = self.conf
        ragged_set = conf.is_set(rc.SHUFFLE_SLOT_RAGGED_ENABLED)
        staging_set = conf.is_set(rc.EXCHANGE_HOST_STAGING_THRESHOLD)
        ev = self.evidence_for(site)
        rows = float(ev.get("rows") or 0.0)
        skew = float(ev.get("skew") or 0.0)
        useful = float(ev.get("bytes") or 0.0)
        w = W_DCN_BYTE if strategy == "gather" else W_ICI_BYTE
        pad_factor = max(nshards * nshards * skew, 1.0) if skew else 1.0
        costs: Dict[str, float] = {}
        if useful:
            costs["uniform"] = useful * pad_factor * w
            costs["ragged"] = useful * RAGGED_WIRE_OVERHEAD * w + \
                RAGGED_ROUNDS_EST * W_PERMUTE_ROUND
            costs["staged"] = useful * W_STAGED_BYTE + W_STAGED_FIXED
        staging_thr = None if staging_set else self._derived_staging_thr()
        # staged is a FITTING decision (budget threshold), ragged a
        # SPEED decision (cost argmin): a payload the device budget
        # could never comfortably hold stages regardless of speed
        if strategy == "gather":
            mode = "gather"
        elif not staging_set and useful and \
                useful * pad_factor > staging_thr:
            mode = "staged"
        elif costs and costs["ragged"] < costs["uniform"] and not (
                ragged_set and
                not conf.get(rc.SHUFFLE_SLOT_RAGGED_ENABLED)):
            mode = "ragged"
        else:
            mode = "uniform"
        if ragged_set:
            ragged = conf.get(rc.SHUFFLE_SLOT_RAGGED_ENABLED)
            min_savings = conf.get(rc.SHUFFLE_SLOT_RAGGED_FACTOR)
        else:
            ragged = mode == "ragged"
            min_savings = RAGGED_MODEL_MIN_SAVINGS
        self._decide(
            "exchange", site, mode, alternatives=costs,
            override=ragged_set or staging_set, evidence=bool(ev),
            predicted=costs.get(mode))
        return ExchangePlan(mode, ragged, min_savings, staging_thr)

    def note_exchange(self, site, *, rows: float, max_slice: float,
                      useful_bytes: float) -> None:
        """Launch-time evidence feed for an exchange-bearing site: the
        measured useful rows, hottest-slice fraction, and useful
        payload bytes — what the NEXT plan-time decision (and a warm
        start's) reads."""
        rows = float(rows)
        self.observe_site(site, rows=rows,
                          skew=round(float(max_slice) / max(rows, 1.0),
                                     6),
                          bytes=float(useful_bytes))

    def observe_staged(self, site, staged_bytes: float) -> None:
        """Staged-launch ledger outcome: the compressed bytes that
        actually crossed host RAM, in the staged leg's cost units."""
        self.observe_outcome("exchange", site,
                             float(staged_bytes) * W_STAGED_BYTE)

    def check_contradiction(self, site, op: str, *, counts, capacity,
                            nshards: int, slot: int) -> None:
        """Post-launch contradiction check: the launch ran the UNIFORM
        slot; if the measured histogram shows a ragged plan would have
        cut wire rows past the hysteresis band, record the
        contradiction and (once per query, replanning armed) raise the
        retryable :class:`ReplanRequested` — completed stages splice
        from checkpoints, only this subtree re-plans, and the evidence
        already folded makes the re-plan choose ragged."""
        import numpy as np
        from spark_rapids_tpu.config import rapids_conf as rc
        if self.conf.is_set(rc.SHUFFLE_SLOT_RAGGED_ENABLED) and \
                not self.conf.get(rc.SHUFFLE_SLOT_RAGGED_ENABLED):
            return  # the user forced uniform: override wins
        from spark_rapids_tpu.parallel.shuffle import plan_ragged
        counts = np.asarray(counts)
        if counts.ndim != 2 or not counts.size:
            return
        rp = plan_ragged(counts, capacity, RAGGED_MODEL_MIN_SAVINGS)
        if rp is None:
            return
        uniform_rows = nshards * counts.shape[1] * max(int(slot), 1)
        ratio = uniform_rows / max(rp.wire_rows(nshards), 1)
        if ratio < self.hysteresis:
            return
        sid = site_id(site)
        rec = self._decide(
            "replan", site, "ragged",
            alternatives={"uniform": float(uniform_rows),
                          "ragged": float(rp.wire_rows(nshards))},
            evidence=True, predicted=float(rp.wire_rows(nshards)))
        rec["observed"] = float(uniform_rows)
        rec["op"] = op
        # "applied" separates a RECORDED contradiction (replanning off,
        # or the one-per-query budget spent) from an actual re-drive
        rec["applied"] = False
        if not self.replan_conf:
            return
        from spark_rapids_tpu.serving import context as qc
        ctx = qc.current()
        if ctx is None or getattr(ctx, "_cm_replanned", False):
            return  # at most ONE replan per query — never oscillate
        ctx._cm_replanned = True
        rec["applied"] = True
        self.replan_count += 1
        from spark_rapids_tpu.robustness.faults import ReplanRequested
        raise ReplanRequested(f"{op}:{sid[:12]}", "uniform", "ragged",
                              ratio)

    # ------------------------------------------------- other knobs --
    def slot_prior(self, site) -> int:
        """Cold-site slot prior for the SlotPlanner: the persisted
        rows x skew estimate of the site's max slice, so a fresh
        process's first launch lands in the same power-of-two bucket
        as the last one's (stable slot = stable jit key = zero-compile
        warm start — warm plans, not just warm executables)."""
        ev = self.evidence_for(site)
        rows = float(ev.get("rows") or 0.0)
        skew = float(ev.get("skew") or 0.0)
        est = int(rows * skew)
        if est > 0:
            self._decide("slot", site, f"prior:{est}", evidence=True,
                         predicted=float(est))
        return est

    def fusion_chain_limit(self) -> int:
        """Fusion chain boundary: the conf default, halved when the
        observed worst compile cost says long chains are compile-bound
        (compile_ms evidence comes from the jit.trace spans the
        tracing runtime persists per site)."""
        from spark_rapids_tpu.config import rapids_conf as rc
        default = self.conf.get(rc.FUSION_MAX_OPS)
        if self.conf.is_set(rc.FUSION_MAX_OPS):
            self._decide("fusion", None, str(default), override=True)
            return default
        import time as _time
        worst, at = self._compile_worst
        now = _time.monotonic()
        if now - at > 5.0:
            # refresh at most every 5s: compile_ms is max-merged and
            # moves rarely, but the TRACING runtime writes it into the
            # shared store behind our back, so a pure running max
            # maintained here would miss its updates
            worst = 0.0
            for recs in (self._store_snapshot(), self.evidence):
                for rec in recs.values():
                    worst = max(worst,
                                float(rec.get("compile_ms") or 0.0))
            self._compile_worst = (worst, now)
        limit = max(4, default // 2) if worst > COMPILE_HEAVY_MS \
            else default
        self._decide("fusion", None, str(limit),
                     alternatives={"default": float(default),
                                   "worstCompileMs": worst},
                     evidence=worst > 0)
        return limit

    def encoded_execution(self) -> bool:
        """Coded-vs-decoded execution: with the conf unset the model
        enables encoded execution (built-in prior: the coded fused
        stage beat the decoded path ~1.8x; the dictionary-overflow
        latch and the planner's equality-faithfulness gates still
        bound it per shape — wrong shapes run decoded regardless)."""
        from spark_rapids_tpu.config import rapids_conf as rc
        if self.conf.is_set(rc.ENCODING_EXECUTION_ENABLED):
            v = self.conf.get(rc.ENCODING_EXECUTION_ENABLED)
            self._decide("encoding", None,
                         "encoded" if v else "decoded", override=True)
            return v
        self._decide("encoding", None, "encoded",
                     alternatives={"encoded": 1.0,
                                   "decoded": ENCODED_SPEEDUP_PRIOR},
                     predicted=1.0)
        return True

    def wire_encoding(self) -> bool:
        """Compressed device wire for dictionary-code columns: free
        bytes to crush (the corrupt-delta fallback keeps it safe), so
        the model enables it whenever the conf leaves it unset."""
        from spark_rapids_tpu.config import rapids_conf as rc
        if self.conf.is_set(rc.ENCODING_WIRE_ENABLED):
            v = self.conf.get(rc.ENCODING_WIRE_ENABLED)
            self._decide("wire", None, "encoded" if v else "wide",
                         override=True)
            return v
        self._decide("wire", None, "encoded",
                     alternatives={"encoded": 1.0, "wide": 2.0},
                     predicted=1.0)
        return True

    def coalesce_goal_bytes(self, default: int) -> int:
        """Coalesce goal: the conf default, capped to a fraction of
        the device budget so planner-inserted coalesces never build a
        batch the spill watermark immediately has to break up."""
        from spark_rapids_tpu.config import rapids_conf as rc
        if self.conf.is_set(rc.BATCH_SIZE_BYTES):
            self._decide("coalesce", None, str(default), override=True)
            return default
        goal = min(int(default),
                   max(self._device_budget() // COALESCE_BUDGET_DIVISOR,
                       COALESCE_GOAL_FLOOR))
        self._decide("coalesce", None, str(goal),
                     alternatives={"confDefault": float(default),
                                   "budgetCap": float(goal)})
        return goal

    # ----------------------------------------------- per-op weights --
    def fold_op_metrics(self, metrics: Dict[str, Dict[str, int]]
                        ) -> None:
        """Fold a query's per-node metrics into readable ``op:<Name>``
        evidence records (observed device us/row per operator kind) —
        the evidence half of the CBO unification: the CPU-vs-TPU
        region decision reads these over the calibration file."""
        try:
            for path, m in metrics.items():
                name = _OP_NAMES.get(path.rsplit(".", 1)[-1])
                if name is None:
                    continue
                rows = int(m.get("numOutputRows") or 0)
                self_ns = int(m.get("opTimeSelf") or 0)
                if rows <= 0 or self_ns <= 0:
                    continue
                # stored in NS/row: the store rounds every field to 3
                # decimals, and a sub-microsecond-per-row operator
                # stored in us/row would round to 0.0 — a "free" op
                # that would poison every CBO region decision
                self._observe_sid(
                    f"op:{name}",
                    tpu_ns_per_row=round(self_ns / rows, 3),
                    rows=float(rows))
        except Exception:
            pass  # metric folding is an optimization, never a failure

    def op_weights(self) -> Dict[str, float]:
        """Observed per-op device weights (us/row) for the CBO — from
        the live store plus the persisted load; empty entries fall
        back to the calibration file / built-in table."""
        out: Dict[str, float] = {}
        for recs in (self.evidence, self._store_snapshot()):
            for sid, rec in recs.items():
                if sid.startswith("op:") and \
                        float(rec.get("tpu_ns_per_row") or 0.0) > 0:
                    out[sid[3:]] = float(rec["tpu_ns_per_row"]) / 1e3
        return out


def active_model(session=None) -> Optional[CostModel]:
    """The active session's cost model, or None (the knobs-off fast
    path — a consumption site pays one getattr + None check)."""
    if session is None:
        from spark_rapids_tpu.api.session import TpuSession
        session = TpuSession._active
    if session is None:
        return None
    return getattr(session, "cost_model", None)


def model_for_conf(conf) -> Optional[CostModel]:
    """The active model, but ONLY when the calling conf itself arms
    the cost model: knobs-off parity is per-CONF, not per-process —
    planning one session's conf while a different (model-on) session
    is ``TpuSession._active`` must neither consult the other
    session's model nor leak decisions into its ledger."""
    if conf is None:
        return None
    from spark_rapids_tpu.config import rapids_conf as rc
    if not conf.get(rc.COSTMODEL_ENABLED):
        return None
    return active_model()


def consumer_staging_threshold(consumer) -> int:
    """Effective host-staging threshold for a consumer wired through
    :func:`resolve_consumer_exchange`: the model's budget-derived
    value when it owns the knob (conf unset), else the conf helper's
    semantics."""
    if getattr(consumer, "_cost_model", None) is not None and \
            consumer._staging_thr is not None:
        return consumer._staging_thr
    from spark_rapids_tpu.parallel.exchange_async import (
        staging_threshold)
    return staging_threshold()


# sentinel: "resolve the active session's model" — the default for
# consumers constructed directly (kernel tests, the dryrun); the
# distributed planner passes ITS session's model (or None) explicitly
# so a concurrent session flipping TpuSession._active mid-construction
# can never leak its model into another session's plan
AUTO_MODEL = "auto"


def resolve_consumer_exchange(consumer, op: str,
                              model=AUTO_MODEL) -> None:
    """Shared consumer-side hookup for the exchange-bearing operators
    (DistributedAggregate / DistributedHashJoin): stamp the consumer
    with the model's plan-time exchange decision — or the inert None
    attributes when no model is active — so the two classes cannot
    diverge."""
    cm = active_model() if isinstance(model, str) and \
        model == AUTO_MODEL else model
    consumer._cost_model = cm
    consumer._planned_mode = None
    consumer._staging_thr = None
    if cm is not None:
        xp = cm.resolve_exchange(consumer._sig, consumer.nshards,
                                 op=op,
                                 strategy=consumer.exchange_strategy)
        consumer.ragged = xp.ragged
        consumer.ragged_min_savings = xp.min_savings
        consumer._planned_mode = xp.mode
        consumer._staging_thr = xp.staging_thr
