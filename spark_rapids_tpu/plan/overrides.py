"""TpuOverrides: plan-replacement rules + meta/tagging framework.

Counterpart of ``GpuOverrides.scala`` (rule registry, ``GpuOverrides.apply``)
and ``RapidsMeta.scala`` (the wrap/tag/convert lifecycle): every logical node
and expression is wrapped in a Meta carrying "will not work on TPU because…"
reasons; supported subtrees convert to TpuExec operators, unsupported ones
fall back to CPU (pandas) execs — the analog of leaving Spark ops on CPU —
and ``explain()`` renders the reasons like `spark.rapids.sql.explain=ALL`.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Type

from spark_rapids_tpu.config.rapids_conf import RapidsConf
from spark_rapids_tpu.ops import arithmetic as arith
from spark_rapids_tpu.ops import predicates as preds
from spark_rapids_tpu.ops.cast import Cast
from spark_rapids_tpu.ops.expressions import (
    Alias, BoundReference, Expression, Literal, ParamSlot, UnresolvedColumn)
from spark_rapids_tpu.plan import logical as L
from spark_rapids_tpu.plan import typechecks as ts
from spark_rapids_tpu.plan.logical import AggregateExpression


# ------------------------------------------------------- expression registry --

class ExprRule:
    def __init__(self, cls: Type[Expression], sig: ts.TypeSig,
                 note: str = "", incompat: str = ""):
        self.cls = cls
        self.sig = sig
        self.note = note
        # non-empty = documented semantics difference vs CPU Spark; runs
        # only when spark.rapids.sql.incompatibleOps.enabled
        # (RapidsMeta.scala:271 incompat tier)
        self.incompat = incompat


_EXPR_RULES: Dict[Type[Expression], ExprRule] = {}


def expr_rule(cls, sig=ts.COMMON, note="", incompat=""):
    _EXPR_RULES[cls] = ExprRule(cls, sig, note, incompat)


# leaves / structural (ParamSlot: a hoisted literal — plan/template.py)
for c in (Alias, BoundReference, Literal, ParamSlot, UnresolvedColumn, Cast):
    expr_rule(c)
# aggregates may produce arrays (collect_list/collect_set)
expr_rule(AggregateExpression, ts.ALL)

from spark_rapids_tpu.exec.window import WindowExpression  # noqa: E402

expr_rule(WindowExpression)

# strings (stringFunctions.scala analog)
from spark_rapids_tpu.ops import stringops as S  # noqa: E402

for c in (S.Length, S.OctetLength, S.StartsWith, S.EndsWith, S.Contains,
          S.Like, S.EqualsLiteral, S.StringLocate, S.Substring,
          S.StringTrim, S.StringTrimLeft, S.StringTrimRight,
          S.ConcatStrings, S.StringRepeat, S.StringLPad, S.StringRPad,
          S.SubstringIndex):
    expr_rule(c, ts.COMMON)
for c in (S.Upper, S.Lower, S.InitCap):
    expr_rule(c, ts.COMMON, incompat="ASCII-only case mapping")
expr_rule(S.Ascii, ts.COMMON)
expr_rule(S.Chr, ts.COMMON)

# date/time (datetimeExpressions.scala analog)
from spark_rapids_tpu.ops import datetime_ops as D  # noqa: E402

for c in (D.Year, D.Month, D.DayOfMonth, D.Quarter, D.DayOfWeek, D.WeekDay,
          D.DayOfYear, D.LastDay, D.Hour, D.Minute, D.Second, D.DateAdd,
          D.DateSub, D.DateDiff, D.AddMonths, D.MonthsBetween, D.TruncDate,
          D.UnixTimestamp, D.FromUnixTime, D.TimeAdd, D.DateFormatClass,
          D.TimeWindow, D.NextDay):
    expr_rule(c, ts.COMMON)
# GetJsonObject / StringSplit (ops/json_ops.py) have NO rule on purpose:
# they are host-only (CPU fallback + distributed dictionary lowering)

# arithmetic + math (numeric only)
for c in (arith.Add, arith.Subtract, arith.Multiply, arith.Divide,
          arith.IntegralDivide, arith.Remainder, arith.Pmod,
          arith.UnaryMinus, arith.UnaryPositive, arith.Abs, arith.Sqrt,
          arith.Cbrt, arith.Exp, arith.Expm1, arith.Log, arith.Log2,
          arith.Log10, arith.Log1p, arith.Sin, arith.Cos, arith.Tan,
          arith.Cot, arith.Asin, arith.Acos, arith.Atan, arith.Sinh,
          arith.Cosh, arith.Tanh, arith.Asinh, arith.Acosh, arith.Atanh,
          arith.ToDegrees, arith.ToRadians, arith.Rint, arith.Signum,
          arith.Floor, arith.Ceil, arith.Pow, arith.Logarithm, arith.Atan2,
          arith.Round, arith.BRound, arith.BitwiseAnd, arith.BitwiseOr,
          arith.BitwiseXor, arith.BitwiseNot, arith.ShiftLeft,
          arith.ShiftRight, arith.ShiftRightUnsigned, arith.Rand,
          arith.Hypot):
    expr_rule(c, ts.NUMERIC)

# decimal plumbing (GpuOverrides.scala:824-838 PromotePrecision /
# CheckOverflow pair + MakeDecimal / UnscaledValue); arithmetic fuses
# the wrappers, the named forms exist for programmatic plans
from spark_rapids_tpu.ops import decimal_ops as DEC  # noqa: E402

for c in (DEC.PromotePrecision, DEC.CheckOverflow, DEC.MakeDecimal,
          DEC.UnscaledValue):
    expr_rule(c, ts.NUMERIC)

# regex family + remaining string surface (stringFunctions.scala +
# shim RegExpReplace rules; unsupported patterns tag off like the
# reference's incompat flag)
from spark_rapids_tpu.ops import regexops as RX  # noqa: E402

for c in (RX.StringReplace, RX.ConcatWs, RX.Translate):
    expr_rule(c, ts.COMMON)
for c in (RX.RLike, RX.RegExpReplace, RX.SplitPart):
    expr_rule(c, ts.COMMON,
              incompat="byte-semantics regex ('.' matches one byte)")

# collections (collectionOperations.scala + complexType rules analog)
from spark_rapids_tpu.ops import collections_ops as C  # noqa: E402

expr_rule(C.CreateArray, ts.ARRAY)
expr_rule(C.SortArray, ts.ARRAY)
expr_rule(C.Size, ts.COMMON)
expr_rule(C.ArrayContains, ts.COMMON)
expr_rule(C.GetArrayItem, ts.COMMON)
expr_rule(C.ElementAt, ts.COMMON)
# ArrayMin/ArrayMax output the ELEMENT type (the sig check runs against
# expr.dtype) — a fixed-width scalar sig both admits the rule and
# constrains the array's element type to what the segment-reduce kernel
# handles (round-4 advisor: ts.ARRAY rejected every scalar output, so
# these silently fell back to CPU).
expr_rule(C.ArrayMin, ts.BOOLEAN + ts.NUMERIC)
expr_rule(C.ArrayMax, ts.BOOLEAN + ts.NUMERIC)
expr_rule(C.Slice, ts.ARRAY)
expr_rule(C.ArrayRepeat, ts.ARRAY,
          incompat="array_repeat(NULL, n) yields a NULL row, not an "
                   "array of nulls (null elements have no device "
                   "representation)")
expr_rule(C.Reverse, ts.COMMON + ts.ARRAY,
          incompat="string reverse is byte-wise (ASCII-only)")

# nested struct/map (complexTypeCreator/Extractors analog; most of these
# compile away at bind time — see ops/nested_ops.py)
from spark_rapids_tpu.ops import nested_ops as NO  # noqa: E402

expr_rule(NO.GetStructField, ts.COMMON)
expr_rule(NO.CreateNamedStruct, ts.COMMON)
expr_rule(NO.CreateMap, ts.COMMON)
expr_rule(NO.MapKeys, ts.COMMON)
expr_rule(NO.MapValues, ts.COMMON)
expr_rule(NO.GetMapValue, ts.COMMON)

# misc (HashFunctions.scala, GpuMonotonicallyIncreasingID analogs)
from spark_rapids_tpu.ops import misc_exprs as ME  # noqa: E402

expr_rule(ME.Murmur3Hash, ts.COMMON)
# Md5 has NO rule: it is host-only and always falls back

# UDFs: a user jax function fuses into the stage (RapidsUDF analog)
from spark_rapids_tpu.udf.python_exec import JaxUDF  # noqa: E402

expr_rule(JaxUDF, ts.ALL)

# Expand (rollup/cube/grouping sets lowering, GpuExpandExec rule analog
# — reference GpuOverrides.scala:3170): typed NULL slots for the
# aggregated-away keys
from spark_rapids_tpu.exec.expand import NullLiteral  # noqa: E402

expr_rule(NullLiteral, ts.ALL)

# predicates / conditionals (any common type flows through)
for c in (preds.EqualTo, preds.EqualNullSafe, preds.LessThan,
          preds.LessThanOrEqual, preds.GreaterThan, preds.GreaterThanOrEqual,
          preds.And, preds.Or, preds.Not, preds.IsNull, preds.IsNotNull,
          preds.IsNaN, preds.NaNvl, preds.Coalesce, preds.If, preds.CaseWhen,
          preds.In, preds.InSet, preds.Greatest, preds.Least,
          preds.AtLeastNNonNulls, preds.KnownNotNull,
          preds.KnownFloatingPointNormalized, preds.NormalizeNaNAndZero):
    expr_rule(c)


# --------------------------------------------------------------- meta classes --

class BaseMeta:
    def __init__(self, wrapped, conf: RapidsConf):
        self.wrapped = wrapped
        self.conf = conf
        self.reasons: List[str] = []
        self.child_metas: List[BaseMeta] = []

    def will_not_work(self, reason: str) -> None:
        self.reasons.append(reason)

    @property
    def can_replace(self) -> bool:
        return not self.reasons and all(
            c.can_replace for c in self.child_metas)

    def tag(self) -> None:
        raise NotImplementedError

    def explain_lines(self, depth: int = 0, all_nodes: bool = True
                      ) -> List[str]:
        status = "will run on TPU" if not self.reasons else \
            "will NOT run on TPU because " + "; ".join(self.reasons)
        name = type(self.wrapped).__name__
        lines = []
        if all_nodes or self.reasons:
            lines.append("  " * depth + f"{'*' if not self.reasons else '!'}"
                         f" {name} {status}")
        for c in self.child_metas:
            lines.extend(c.explain_lines(depth + 1, all_nodes))
        return lines


class ExprMeta(BaseMeta):
    def __init__(self, expr: Expression, conf: RapidsConf):
        super().__init__(expr, conf)
        self.child_metas = [ExprMeta(c, conf) for c in expr.children]
        if isinstance(expr, AggregateExpression) and \
                expr.func.child is not None:
            self.child_metas = [ExprMeta(expr.func.child, conf)]

    def tag(self) -> None:
        from spark_rapids_tpu.ops.cast import cast_supported
        expr = self.wrapped
        name = type(expr).__name__
        if not self.conf.op_enabled("expression", name):
            self.will_not_work(
                f"expression {name} disabled by "
                f"spark.rapids.sql.expression.{name}")
        rule = _EXPR_RULES.get(type(expr))
        if rule is not None and rule.incompat:
            from spark_rapids_tpu.config.rapids_conf import INCOMPAT_ENABLED
            if not self.conf.get(INCOMPAT_ENABLED):
                self.will_not_work(
                    f"{name} is incompatible with CPU Spark "
                    f"({rule.incompat}) and "
                    "spark.rapids.sql.incompatibleOps.enabled is false")
        if isinstance(expr, AggregateExpression):
            try:
                reason = expr.func.supported_reason()
                if reason:
                    self.will_not_work(reason)
                if expr.dtype.is_array and not getattr(
                        expr.func, "single_pass", False):
                    self.will_not_work(
                        f"aggregate {expr.func.name} over array values "
                        "not supported (only collect_list/collect_set "
                        "produce arrays)")
                child = expr.func.child
                if child is not None and child.dtype.has_offsets and \
                        expr.func.name not in ("count", "min", "max",
                                               "first", "last") and \
                        not getattr(expr.func, "single_pass", False):
                    # string min/max/first/last run via batch-local
                    # order-preserving dictionary codes
                    # (exec/aggregate.py); sum/avg over offset columns
                    # have no numeric meaning on device
                    self.will_not_work(
                        f"aggregate {expr.func.name} over "
                        f"{child.dtype.name} values falls back to CPU")
            except (RuntimeError, TypeError, ValueError) as e:
                self.will_not_work(str(e))
        if isinstance(expr, Cast):
            try:
                reason = cast_supported(expr.child.dtype, expr.target)
                if reason:
                    self.will_not_work(reason)
            except (RuntimeError, TypeError, ValueError):
                pass
        if isinstance(expr, C.CreateArray) and any(
                c.nullable for c in expr.children):
            self.will_not_work(
                "array() over nullable children not supported on TPU "
                "(null array elements have no device representation); "
                "falls back to CPU")
        if isinstance(expr, S.Like) and not expr.supported:
            self.will_not_work(
                f"LIKE pattern {expr.pattern!r} too general for TPU")
        if isinstance(expr, D.DateFormatClass) and not expr.supported:
            self.will_not_work(
                f"date_format pattern {expr.fmt!r} outside the "
                "fixed-width device subset (yyyy/MM/dd/HH/mm/ss)")
        if isinstance(expr, (RX.RLike, RX.RegExpReplace, RX.StringReplace,
                             RX.Translate, RX.SplitPart)) and \
                not expr.supported:
            self.will_not_work(
                f"{type(expr).__name__} arguments outside the TPU regex "
                "subset (falls back to CPU, like the reference's regex "
                "incompat flag)")
        if isinstance(expr, (RX.RLike, RX.RegExpReplace, RX.SplitPart)):
            from spark_rapids_tpu.config.rapids_conf import REGEXP_ENABLED
            if not self.conf.get(REGEXP_ENABLED):
                self.will_not_work(
                    f"{name} disabled by "
                    "spark.rapids.sql.regexp.enabled")
        if isinstance(expr, AggregateExpression) and \
                expr.func.name in ("sum", "avg", "average", "mean",
                                   "var_pop", "var_samp", "stddev_pop",
                                   "stddev_samp") and \
                expr.func.child is not None:
            try:
                is_float = expr.func.child.dtype.is_floating
            except (RuntimeError, TypeError, ValueError):
                is_float = False  # dtype issues already tagged above
            from spark_rapids_tpu.config.rapids_conf import \
                VARIABLE_FLOAT_AGG
            if is_float and not self.conf.get(VARIABLE_FLOAT_AGG):
                self.will_not_work(
                    f"float {expr.func.name} reorders additions across "
                    "chunks/shards and "
                    "spark.rapids.sql.variableFloatAgg.enabled is false")
        if isinstance(expr, Cast):
            from spark_rapids_tpu.config import rapids_conf as _rc
            try:
                src, dst = expr.child.dtype, expr.target
                gates = (
                    (src.is_string and dst.is_floating,
                     _rc.CAST_STRING_TO_FLOAT),
                    (src.is_floating and dst.is_string,
                     _rc.CAST_FLOAT_TO_STRING),
                    (src.is_floating and dst.is_decimal,
                     _rc.CAST_FLOAT_TO_DECIMAL),
                    (src.is_string and (dst.is_timestamp or dst.is_date),
                     _rc.CAST_STRING_TO_TIMESTAMP),
                )
                for hit, entry in gates:
                    if hit and not self.conf.get(entry):
                        self.will_not_work(
                            f"cast {src.name}->{dst.name} disabled by "
                            f"{entry.key}")
            except (RuntimeError, TypeError, ValueError):
                pass
        if isinstance(expr, WindowExpression):
            reason = expr.supported_reason()
            if reason:
                self.will_not_work(reason)
            if any(e.dtype.is_string for e, _, _ in expr.spec.orders):
                self.will_not_work("string window order keys not supported")
            for c in self.child_metas:
                c.tag()
            return
        if rule is None:
            self.will_not_work(
                f"expression {name} has no TPU implementation")
        else:
            try:
                dt = expr.dtype
                if dt.is_decimal and not self.conf[
                        "spark.rapids.sql.decimalType.enabled"]:
                    self.will_not_work(
                        "decimal is disabled by "
                        "spark.rapids.sql.decimalType.enabled")
                reason = rule.sig.reason_if_unsupported(
                    dt, f"expression {type(expr).__name__}")
                if reason and not isinstance(expr, (BoundReference, Alias,
                                                    Literal)):
                    self.will_not_work(reason)
            except (RuntimeError, TypeError, ValueError) as e:
                self.will_not_work(str(e))
        for c in self.child_metas:
            c.tag()


class PlanMeta(BaseMeta):
    """Wraps a logical node; conversion handled by the planner below."""

    def __init__(self, plan: L.LogicalPlan, conf: RapidsConf):
        super().__init__(plan, conf)
        self.child_metas = [PlanMeta(c, conf) for c in plan.children]
        self.expr_metas: List[ExprMeta] = [
            ExprMeta(e, conf) for e in _node_expressions(plan)]

    def tag(self) -> None:
        node = self.wrapped
        if not self.conf.op_enabled("exec", type(node).__name__):
            self.will_not_work(
                f"{type(node).__name__} disabled by "
                f"spark.rapids.sql.exec.{type(node).__name__}")
        if isinstance(node, L.FileRelation):
            # per-format scan switches (sql.format.<fmt>.enabled /
            # .read.enabled, RapidsConf.scala:664): a disabled format
            # runs the whole read on the pandas fallback chain
            from spark_rapids_tpu.config import rapids_conf as _rc
            gates = {"parquet": (_rc.PARQUET_ENABLED,
                                 _rc.PARQUET_READ_ENABLED),
                     "orc": (_rc.ORC_ENABLED, _rc.ORC_READ_ENABLED),
                     "csv": (_rc.CSV_ENABLED, _rc.CSV_READ_ENABLED)}
            for entry in gates.get(node.file_format, ()):
                if not self.conf.get(entry):
                    self.will_not_work(
                        f"{node.file_format} scan disabled by "
                        f"{entry.key}")
        if type(node) not in _PLAN_CONVERTERS:
            self.will_not_work(
                f"{type(node).__name__} has no TPU implementation")
        # array<string> exists only on the host surface (dictionary-coded
        # Column with a host string table no device exec preserves): any
        # node CONSUMING one must stay on the CPU fallback chain
        for c in node.children:
            for cn, cdt in c.schema:
                if cdt.is_array and cdt.element is not None and \
                        cdt.element.is_string:
                    self.will_not_work(
                        f"input column {cn!r} is array<string>, a "
                        "host-only type (no device representation)")
        if isinstance(node, L.Sort) and any(
                e.dtype.is_array for e, _, _ in node.orders):
            self.will_not_work("array sort keys not supported on TPU")
        if isinstance(node, L.Aggregate) and any(
                e.dtype.is_array for e in node.group_exprs):
            self.will_not_work("array group-by keys not supported on TPU")
        if isinstance(node, L.Aggregate):
            funcs = [x.func for e in node.agg_exprs
                     for x in _walk_aggs(e)]
            if any(getattr(f, "single_pass", False) for f in funcs) and \
                    any(f.child is not None and f.child.dtype.has_offsets
                        and not getattr(f, "single_pass", False)
                        for f in funcs):
                # the single-pass (collect) execution path has no
                # dictionary staging for string min/max siblings
                self.will_not_work(
                    "collect aggregates combined with string-valued "
                    "min/max/first/last fall back to CPU")
        if isinstance(node, L.Generate) and not \
                node.generator.dtype.is_array:
            self.will_not_work(
                f"explode needs an array column, got "
                f"{node.generator.dtype}")
        if isinstance(node, L.Join):
            if node.condition is not None and node.join_type != "inner":
                self.will_not_work(
                    "non-equi join conditions only supported for inner "
                    "joins on TPU (outer residual semantics need the "
                    "nested-loop join)")
            for lk, rk in zip(node.left_keys, node.right_keys):
                if lk.dtype.name != rk.dtype.name:
                    self.will_not_work(
                        f"join key type mismatch {lk.dtype} vs {rk.dtype}")
                if lk.dtype.is_array:
                    self.will_not_work(
                        "array join keys not supported on TPU")
        for em in self.expr_metas:
            em.tag()
            if not em.can_replace:
                deep = _deep_reasons(em)
                detail = "; ".join(deep) if deep else "unsupported"
                self.will_not_work(
                    f"expression {type(em.wrapped).__name__} cannot run on "
                    f"TPU: {detail}")
        for c in self.child_metas:
            c.tag()

    def explain_lines(self, depth: int = 0, all_nodes: bool = True):
        lines = super().explain_lines(depth, all_nodes)
        for em in self.expr_metas:
            if em.reasons:
                lines.extend(em.explain_lines(depth + 1, False))
        return lines


def _walk_aggs(e: Expression) -> List[AggregateExpression]:
    out = []
    if isinstance(e, AggregateExpression):
        out.append(e)
    for c in e.children:
        out.extend(_walk_aggs(c))
    return out


def _deep_reasons(meta: BaseMeta) -> List[str]:
    """All will-not-work reasons in an expression meta tree (the inner
    reason, e.g. a per-op disable, is what the user needs to see)."""
    out = list(meta.reasons)
    for c in meta.child_metas:
        out.extend(_deep_reasons(c))
    return out


def _node_expressions(plan: L.LogicalPlan) -> List[Expression]:
    from spark_rapids_tpu.exec.expand import Expand
    if isinstance(plan, Expand):
        return [e for p in plan.projections for e in p]
    if isinstance(plan, L.Project):
        return list(plan.exprs)
    if isinstance(plan, L.Generate):
        return [plan.generator] + list(plan.required)
    if isinstance(plan, L.Filter):
        return [plan.condition]
    if isinstance(plan, L.Aggregate):
        return list(plan.group_exprs) + list(plan.agg_exprs)
    if isinstance(plan, L.Join):
        return list(plan.left_keys) + list(plan.right_keys)
    if isinstance(plan, L.Sort):
        return [e for e, _, _ in plan.orders]
    if isinstance(plan, L.Window):
        return [e for _, e in plan.window_exprs]
    return []


# ------------------------------------------------------------------ planner --

_PLAN_CONVERTERS: Dict[type, object] = {}


def _converter(cls):
    def deco(fn):
        _PLAN_CONVERTERS[cls] = fn
        return fn
    return deco


@_converter(L.InMemoryRelation)
def _conv_inmemory(node: L.InMemoryRelation, children, conf):
    from spark_rapids_tpu.exec.basic import TpuScanExec
    return TpuScanExec(node.batches, node.schema)


@_converter(L.FileRelation)
def _conv_file(node: L.FileRelation, children, conf):
    from spark_rapids_tpu.io.readers import make_file_scan_exec
    scan = make_file_scan_exec(node, conf)
    # PERFILE readers emit one undersized batch per file: planner-
    # inserted coalesce to the batch goal (GpuTransitionOverrides.
    # scala:57-64).  Other reader types already merge to goal-sized
    # batches, and array<string> columns carry PER-BATCH dictionary
    # codes that concatenation would corrupt — leave those bare.
    if len(node.paths) > 1 and \
            getattr(scan, "reader_type", "") == "PERFILE" and \
            not any(dt.is_array and dt.element is not None
                    and dt.element.is_string for _, dt in node.schema):
        from spark_rapids_tpu.config import rapids_conf as _rc
        from spark_rapids_tpu.exec.basic import TpuCoalesceBatchesExec
        from spark_rapids_tpu.memory.coalesce import TargetSize
        from spark_rapids_tpu.plan.costmodel import model_for_conf
        goal = conf.get(_rc.BATCH_SIZE_BYTES)
        cm = model_for_conf(conf)
        if cm is not None:
            # self-tuning planner: the coalesce goal caps at a
            # fraction of the device budget unless batchSizeBytes was
            # explicitly tuned (the override discipline)
            goal = cm.coalesce_goal_bytes(goal)
        return TpuCoalesceBatchesExec(scan, TargetSize(goal))
    return scan


@_converter(L.Project)
def _conv_project(node: L.Project, children, conf):
    from spark_rapids_tpu.config import rapids_conf as _rc
    from spark_rapids_tpu.exec.basic import TpuProjectExec
    return TpuProjectExec(node.exprs, children[0],
                          donate=conf.get(_rc.PIPELINE_DONATION))


@_converter(L.Filter)
def _conv_filter(node: L.Filter, children, conf):
    from spark_rapids_tpu.config import rapids_conf as _rc
    from spark_rapids_tpu.exec.basic import TpuFilterExec
    return TpuFilterExec(node.condition, children[0],
                         donate=conf.get(_rc.PIPELINE_DONATION))


def _encoding_exec_enabled(conf) -> bool:
    """Encoded execution conf, minus the session's overflow latch (a
    dictionary that outgrew maxDictSize latched the session back onto
    the decoded path; every attempt re-plans, so the latch takes
    effect on the ladder's next rung).  With the self-tuning cost
    model active the model decides the coded-vs-decoded knob when the
    conf leaves it unset (an explicit conf stays an override)."""
    from spark_rapids_tpu.config import rapids_conf as rc
    from spark_rapids_tpu.plan.costmodel import model_for_conf
    cm = model_for_conf(conf)  # conf-gated: knobs-off conf = HEAD
    if cm is not None:
        if not cm.encoded_execution():
            return False
    elif not conf.get(rc.ENCODING_EXECUTION_ENABLED):
        return False
    from spark_rapids_tpu.api.session import TpuSession
    return not getattr(TpuSession._active, "encoding_exec_latched",
                       False)


def _agg_kernel_children(agg_out_exprs) -> List[Expression]:
    """The aggregate-function children inside the output expressions —
    the subtrees the aggregation KERNELS evaluate (everything else in
    an output either matches a group key or reads the agg frame after
    the kernels)."""
    out: List[Expression] = []

    def walk(e):
        if isinstance(e, AggregateExpression):
            if e.func.child is not None:
                out.append(e.func.child)
            return
        for c in e.children:
            walk(c)

    for e in agg_out_exprs:
        walk(e)
    return out


def _agg_fold_encodable(group, aggs, conds) -> bool:
    """True when the fused aggregate fold may run ENCODED over string
    group keys: no string-valued aggregate buffers (those force the
    two-stage string path, which cannot carry a fused predicate) and
    the keys pass the exec's own equality-faithfulness test."""
    from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
    children = _agg_kernel_children(aggs)
    if any(c.dtype.is_string for c in children):
        return False
    return TpuHashAggregateExec.encoded_key_ordinals(
        group, children + list(conds)) is not None


def _plan_aggregate(group_exprs, agg_out_exprs, child_exec,
                    pre_filter=None, merge_chunk_rows=1 << 22,
                    defer_syncs=True, encoded_exec=False,
                    max_dict_size=(1 << 31) - 1):
    """Build the aggregate exec, plus a result projection when outputs
    combine aggregates in larger expressions (sum(x)*100, sum(a)/sum(b)...
    — Catalyst's resultExpressions split)."""
    from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.exec.basic import TpuProjectExec

    nkeys = len(group_exprs)
    agg_list: List[AggregateExpression] = []
    group_keys = [ge.cache_key() for ge in group_exprs]

    def extract(e):
        if isinstance(e, AggregateExpression):
            idx = len(agg_list)
            agg_list.append(e)
            return BoundReference(nkeys + idx, e.dtype, name=f"_a{idx}",
                                  nullable=e.nullable)
        # non-aggregate subtrees matching a group expression read the
        # agg frame's key column, not the child's ordinal (Catalyst
        # rewrites resultExpressions the same way)
        try:
            ck = e.cache_key()
        except Exception:
            ck = None
        if ck is not None and ck in group_keys:
            ki = group_keys.index(ck)
            ge = group_exprs[ki]
            return BoundReference(ki, ge.dtype, name=ge.name,
                                  nullable=ge.nullable)
        if not e.children:
            if isinstance(e, BoundReference):
                raise ValueError(
                    f"column {e.name!r} in aggregate output is neither "
                    "an aggregate nor in the GROUP BY")
            return e
        return e.with_children([extract(c) for c in e.children])

    out_named = []
    trivial = True
    for e in agg_out_exprs:
        name = e.name
        inner = e.children[0] if isinstance(e, Alias) else e
        rewritten = extract(inner)
        if not isinstance(inner, AggregateExpression):
            trivial = False
        out_named.append((name, rewritten))

    if trivial:
        # every output is a bare aggregate: name the agg columns directly
        return TpuHashAggregateExec(
            group_exprs,
            [(name, a) for (name, _), a in zip(out_named, agg_list)],
            child_exec, pre_filter=pre_filter,
            merge_chunk_rows=merge_chunk_rows, defer_syncs=defer_syncs,
            encoded_exec=encoded_exec, max_dict_size=max_dict_size)
    agg_exec = TpuHashAggregateExec(
        group_exprs, [(f"_a{i}", a) for i, a in enumerate(agg_list)],
        child_exec, pre_filter=pre_filter,
        merge_chunk_rows=merge_chunk_rows, defer_syncs=defer_syncs,
        encoded_exec=encoded_exec, max_dict_size=max_dict_size)
    proj = [BoundReference(i, dt, name=n)
            for i, (n, dt) in enumerate(agg_exec.schema[:nkeys])]
    proj += [Alias(rewritten, name) for name, rewritten in out_named]
    return TpuProjectExec(proj, agg_exec)


@_converter(L.Aggregate)
def _conv_aggregate(node: L.Aggregate, children, conf):
    from spark_rapids_tpu.config import rapids_conf as rc
    return _plan_aggregate(node.group_exprs, node.agg_exprs, children[0],
                           merge_chunk_rows=conf.get(rc.AGG_MERGE_CHUNK_ROWS),
                           defer_syncs=conf.get(rc.PIPELINE_DEFER_SYNCS),
                           encoded_exec=_encoding_exec_enabled(conf),
                           max_dict_size=conf.get(
                               rc.ENCODING_EXECUTION_MAX_DICT))


@_converter(L.Limit)
def _conv_limit(node: L.Limit, children, conf):
    from spark_rapids_tpu.exec.basic import TpuLocalLimitExec
    return TpuLocalLimitExec(node.n, children[0])


@_converter(L.Union)
def _conv_union(node: L.Union, children, conf):
    from spark_rapids_tpu.exec.basic import TpuUnionExec
    return TpuUnionExec(*children)


@_converter(L.Range)
def _conv_range(node: L.Range, children, conf):
    from spark_rapids_tpu.exec.basic import TpuRangeExec
    return TpuRangeExec(node.start, node.end, node.step)


@_converter(L.Sort)
def _conv_sort(node: L.Sort, children, conf):
    from spark_rapids_tpu.config import rapids_conf as rc
    from spark_rapids_tpu.exec.sort import TpuSortExec
    return TpuSortExec(
        node.orders, children[0],
        ooc_threshold_bytes=conf.get(rc.SORT_OOC_THRESHOLD),
        ooc_window_rows=conf.get(rc.SORT_OOC_WINDOW_ROWS))


@_converter(L.Join)
def _conv_join(node: L.Join, children, conf):
    from spark_rapids_tpu.exec.basic import TpuFilterExec
    from spark_rapids_tpu.exec.join import TpuHashJoinExec
    join_type = node.join_type
    if node.condition is not None and not node.left_keys:
        # pure non-equi inner join: cross product + filter (the
        # GpuBroadcastNestedLoopJoinExec shape)
        join_type = "cross"
    from spark_rapids_tpu.config import rapids_conf as rc
    join = TpuHashJoinExec(node.left_keys, node.right_keys, join_type,
                           children[0], children[1], using=node.using,
                           max_output_rows=conf.get(
                               rc.JOIN_OUTPUT_BATCH_ROWS))
    if node.condition is not None:
        # residual condition evaluated over the joined output
        return TpuFilterExec(node.condition, join)
    return join


@_converter(L.AggInPandas)
def _conv_agg_in_pandas(node: L.AggInPandas, children, conf):
    from spark_rapids_tpu.udf.python_exec import TpuAggregateInPandasExec
    return TpuAggregateInPandasExec(node.group_names, node.aggs,
                                    children[0])


@_converter(L.WindowInPandas)
def _conv_window_in_pandas(node: L.WindowInPandas, children, conf):
    from spark_rapids_tpu.udf.python_exec import TpuWindowInPandasExec
    return TpuWindowInPandasExec(node.calls, children[0])


@_converter(L.CoGroupMapInPandas)
def _conv_cogroup(node: L.CoGroupMapInPandas, children, conf):
    from spark_rapids_tpu.udf.python_exec import (
        TpuFlatMapCoGroupsInPandasExec)
    return TpuFlatMapCoGroupsInPandasExec(
        node.fn, node.schema, node.left_names, node.right_names,
        children[0], children[1])


@_converter(L.BatchId)
def _conv_batch_id(node: L.BatchId, children, conf):
    from spark_rapids_tpu.ops.misc_exprs import TpuBatchIdExec
    return TpuBatchIdExec(children[0])


@_converter(L.Generate)
def _conv_generate(node: L.Generate, children, conf):
    from spark_rapids_tpu.exec.generate import TpuGenerateExec
    return TpuGenerateExec(node.generator, node.required, node.position,
                           children[0], col_name=node.col_name,
                           pos_name=node.pos_name,
                           generator2=node.generator2)


def _register_expand_converter():
    from spark_rapids_tpu.exec.expand import Expand, TpuExpandExec

    @_converter(Expand)
    def _conv_expand(node, children, conf):
        return TpuExpandExec(node, children[0])


_register_expand_converter()


def _window_one_spec(window_exprs, child_exec, conf):
    from spark_rapids_tpu.config import rapids_conf as rc
    from spark_rapids_tpu.exec.sort import TpuSortExec
    from spark_rapids_tpu.exec.window import TpuWindowExec
    spec = window_exprs[0][1].spec
    if spec.partition_exprs or spec.orders:
        # Spark plans WindowExec above a SortExec on (partition, order);
        # the sort brings the engine's out-of-core machinery, and the
        # window then streams key-aligned chunks instead of
        # materializing its whole input (GpuWindowExec.scala:423-446 +
        # GpuKeyBatchingIterator analog)
        orders = [(e, False, True) for e in spec.partition_exprs] + \
            list(spec.orders)
        sort = TpuSortExec(
            orders, child_exec,
            ooc_threshold_bytes=conf.get(rc.SORT_OOC_THRESHOLD),
            ooc_window_rows=conf.get(rc.SORT_OOC_WINDOW_ROWS))
        return TpuWindowExec(window_exprs, sort, presorted=True,
                             batch_rows=conf.get(rc.WINDOW_BATCH_ROWS))
    return TpuWindowExec(window_exprs, child_exec)


@_converter(L.Window)
def _conv_window(node: L.Window, children, conf):
    from spark_rapids_tpu.exec.basic import TpuProjectExec
    from spark_rapids_tpu.exec.window import group_by_spec
    from spark_rapids_tpu.ops.expressions import Alias, BoundReference
    exprs = node.window_exprs
    nchild = len(children[0].schema)
    groups = group_by_spec(exprs)
    if len(groups) == 1:
        return _window_one_spec(exprs, children[0], conf)
    # multiple specs: chain one TpuWindowExec per spec (later specs see
    # earlier outputs as payload; bound ordinals into the child are
    # unchanged because outputs append at the end), then restore the
    # node's column order (WindowExecBase handles one spec per exec in
    # the reference too — Spark splits them the same way)
    cur = children[0]
    appended_pos: Dict[int, int] = {}
    base = nchild
    for grp in groups:
        cur = _window_one_spec([(n, we) for _, n, we in grp], cur, conf)
        for i, (j, _, _) in enumerate(grp):
            appended_pos[j] = base + i
        base += len(grp)
    cur_schema = cur.schema
    perm = list(range(nchild)) + \
        [appended_pos[j] for j in range(len(exprs))]
    projs = []
    for want_name, p in zip([n for n, _ in node.schema], perm):
        pname, pdt = cur_schema[p]
        projs.append(Alias(BoundReference(p, pdt, pname), want_name))
    return TpuProjectExec(projs, cur)


@_converter(L.MapInPandas)
def _conv_map_in_pandas(node: L.MapInPandas, children, conf):
    from spark_rapids_tpu.udf.python_exec import (
        TpuFlatMapGroupsInPandasExec, TpuMapInPandasExec)
    if node.group_names:
        return TpuFlatMapGroupsInPandasExec(node.fn, node.schema,
                                            node.group_names, children[0])
    return TpuMapInPandasExec(node.fn, node.schema, children[0])


def _pushdown_pass(plan: L.LogicalPlan, cache_manager=None) -> None:
    """Column pruning + predicate pushdown into FileRelations.

    Pruned columns are only those dropped by a Project/Aggregate above, so
    BoundReference ordinals stay valid (the scan emits null placeholders
    for unread columns, which by construction nothing references).
    Filters push down until a Project renames the namespace.

    Cached plan nodes are pushdown BARRIERS: a query-specific filter or
    column pruning pushed below a cache boundary would materialize a
    filtered/pruned subset as the cache, silently poisoning every later
    reader.  At a cached node the pushdown restarts fresh (and, because
    assignments overwrite, clears any pushdown a previous query left on
    the shared FileRelation nodes).
    """
    barrier_entered: set = set()

    def visit(node, required, filters):
        if cache_manager is not None and id(node) not in barrier_entered \
                and cache_manager.lookup(node) is not None:
            barrier_entered.add(id(node))
            visit(node, None, [])
            return
        if isinstance(node, L.FileRelation):
            if required is not None:
                node.required_columns = set(required)
            node.pushed_filters = list(filters)
            return
        if isinstance(node, L.Filter):
            req = None if required is None else \
                set(required) | set(node.condition.references())
            visit(node.child, req, filters + [node.condition])
            return
        if isinstance(node, L.Project):
            refs = set()
            for e in node.exprs:
                refs.update(e.references())
            visit(node.child, refs, [])
            return
        if isinstance(node, L.Aggregate):
            refs = set()
            for e in list(node.group_exprs) + list(node.agg_exprs):
                refs.update(e.references())
            visit(node.child, refs, [])
            return
        for c in node.children:
            visit(c, None, [])

    visit(plan, None, [])


# process-wide planning-pass counter: every TpuOverrides.apply ticks it.
# The template bench pins this at zero across prepared repeats — "skips
# planning entirely" is a measured claim, not a code-path assumption.
_planning_passes = 0


def planning_passes() -> int:
    return _planning_passes


class TpuOverrides:
    """The planner: logical plan -> TpuExec tree with CPU fallback."""

    def __init__(self, conf: Optional[RapidsConf] = None,
                 cache_manager=None):
        from spark_rapids_tpu.config import rapids_conf as _rc
        self.conf = conf or RapidsConf()
        self.last_explain: str = ""
        self.last_cbo: List[str] = []
        self.cache_manager = cache_manager
        self.fusion_enabled = self.conf.get(_rc.FUSION_ENABLED)
        self.fusion_max_ops = self.conf.get(_rc.FUSION_MAX_OPS)
        # per-apply fusion accounting (QueryEnd "fusion" dict): stages/
        # operators actually fused, plus chains that COULD have fused
        # (the health-check signal when fusion is disabled).  Keyed by
        # effective thread ident (the PR6 _current_qid discipline): one
        # overrides instance serves concurrent queries, and a single
        # shared dict would stamp query A's QueryEnd with query B's
        # planned chains.  Bounded: idents recycle, stale entries are
        # pruned once the map outgrows any plausible thread count.
        self._fusion_by_ident: Dict[int, Dict[str, int]] = {}
        self._chain_nodes_by_ident: Dict[int, set] = {}

    @staticmethod
    def _ident() -> int:
        from spark_rapids_tpu.serving import context as qc
        return qc.effective_ident()

    def _fresh_fusion(self) -> Dict[str, int]:
        return {"enabled": self.fusion_enabled, "fusedStages": 0,
                "fusedOperators": 0, "fusibleChains": 0}

    @property
    def last_fusion(self) -> Dict[str, int]:
        # setdefault, not get: a concurrent apply()'s oversized-map
        # prune may drop this ident's dict mid-plan — recreate so a
        # counter bump degrades the metrics, never the query
        return self._fusion_by_ident.setdefault(self._ident(),
                                                self._fresh_fusion())

    @property
    def _counted_chain_nodes(self) -> set:
        return self._chain_nodes_by_ident.setdefault(self._ident(),
                                                     set())

    def apply(self, plan: L.LogicalPlan):
        global _planning_passes
        _planning_passes += 1
        _pushdown_pass(plan, self.cache_manager)
        meta = PlanMeta(plan, self.conf)
        meta.tag()
        ident = self._ident()
        for m in (self._fusion_by_ident, self._chain_nodes_by_ident):
            if len(m) > 256:
                # recycled-ident flood: drop stale entries but keep the
                # concurrently-planning threads' live state (the
                # last_fusion property self-heals regardless)
                for k in list(m)[:128]:
                    if k != ident:
                        m.pop(k, None)
        self._fusion_by_ident[ident] = self._fresh_fusion()
        self._chain_nodes_by_ident[ident] = set()
        from spark_rapids_tpu.plan.costmodel import model_for_conf
        cm = model_for_conf(self.conf)  # conf-gated: see costmodel.py
        if cm is not None:
            # self-tuning planner: fusion chain boundaries come from
            # the one cost model (compile-cost evidence halves the
            # bound; an explicit maxChainOps conf stays an override) —
            # re-resolved per apply so the decision lands in the
            # CURRENT query's ledger
            self.fusion_max_ops = cm.fusion_chain_limit()
        from spark_rapids_tpu.config import rapids_conf as rc
        self.last_cbo = []
        if self.conf.get(rc.CBO_ENABLED):
            from spark_rapids_tpu.plan.cbo import CostBasedOptimizer
            cbo = CostBasedOptimizer(self.conf)
            cbo.optimize(meta)
            self.last_cbo = cbo.explain
        self.last_explain = "\n".join(meta.explain_lines())
        if self.conf.explain == "ALL":
            print(self.last_explain)
        elif self.conf.explain == "NOT_ON_TPU":
            lines = [ln for ln in meta.explain_lines(all_nodes=False)]
            if lines:
                print("\n".join(lines))
        return self._convert(meta)

    def _convert(self, meta: PlanMeta):
        node = meta.wrapped
        if self.cache_manager is not None:
            entry = self.cache_manager.lookup(node)
            if entry is not None:
                from spark_rapids_tpu.exec.cache import (
                    TpuCachedScanExec, TpuMaterializeCacheExec)
                if entry.materialized:
                    return TpuCachedScanExec(entry)
                from spark_rapids_tpu import native
                from spark_rapids_tpu.config import rapids_conf as rc
                return TpuMaterializeCacheExec(
                    entry, self._convert_uncached(meta),
                    codec_level=native.codec_level(
                        self.conf[rc.SHUFFLE_COMPRESSION_CODEC.key]))
        return self._convert_uncached(meta)

    def _convert_uncached(self, meta: PlanMeta):
        node = meta.wrapped
        if isinstance(node, L.Aggregate) and not meta.reasons:
            fused = self._try_fuse_aggregate(meta)
            if fused is not None:
                return fused
        # Limit(Sort) -> TopN (TakeOrderedAndProject analog); not across a
        # cached Sort, whose materialized result must be read/populated
        if isinstance(node, L.Limit) and meta.child_metas and \
                isinstance(meta.child_metas[0].wrapped, L.Sort) and \
                meta.child_metas[0].can_replace and \
                (self.cache_manager is None or
                 self.cache_manager.lookup(meta.child_metas[0].wrapped)
                 is None):
            from spark_rapids_tpu.exec.sort import TpuTopNExec
            sort_meta = meta.child_metas[0]
            base = self._convert(sort_meta.child_metas[0])
            return TpuTopNExec(node.n, sort_meta.wrapped.orders, base)
        if isinstance(node, (L.Project, L.Filter)) and not meta.reasons:
            fused = self._try_fuse_chain(meta)
            if fused is not None:
                return fused
        children = [self._convert(c) for c in meta.child_metas]
        own_ok = not meta.reasons
        if own_ok and type(node) in _PLAN_CONVERTERS:
            return _PLAN_CONVERTERS[type(node)](node, children, self.conf)
        if isinstance(node, L.Project) and self._udf_only_failure(meta):
            # scalar Python UDF projection: device-evaluate everything
            # except the UDF calls themselves (GpuArrowEvalPythonExec)
            from spark_rapids_tpu.udf.python_exec import (
                TpuArrowEvalPythonExec)
            return TpuArrowEvalPythonExec(node.exprs, children[0])
        if self.conf["spark.rapids.sql.test.enabled"]:
            allowed = self.conf[
                "spark.rapids.sql.test.allowedNonTpu"].split(",")
            if type(node).__name__ not in [a.strip() for a in allowed]:
                raise RuntimeError(
                    f"{type(node).__name__} fell back to CPU in strict test "
                    f"mode: {'; '.join(meta.reasons)}")
        from spark_rapids_tpu.exec.fallback import CpuFallbackExec
        return CpuFallbackExec(node, children)

    def _udf_only_failure(self, meta: PlanMeta) -> bool:
        """True when the node's only obstacles are black-box PythonUDF
        calls (everything around them is TPU-supported): re-tag each
        expression with UDF subtrees replaced by typed placeholders."""
        from spark_rapids_tpu.ops.expressions import BoundReference
        from spark_rapids_tpu.udf.python_exec import (
            _find_python_udfs, _replace_udfs)
        # (child failures need no handling here: each child converts with
        # its own fallback independently)
        node = meta.wrapped
        found = False
        for e in node.exprs:
            udfs = _find_python_udfs(e)
            if any(_find_python_udfs(a) for u in udfs
                   for a in u.children):
                return False  # nested black-box UDFs: whole-plan fallback
            if not udfs:
                em = ExprMeta(e, self.conf)
                em.tag()
                if not em.can_replace:
                    return False
                continue
            found = True
            mapping = {id(u): BoundReference(0, u.return_type,
                                             name="_udf")
                       for u in udfs}
            em = ExprMeta(_replace_udfs(e, mapping), self.conf)
            em.tag()
            if not em.can_replace:
                return False
        return found

    def _fusible_member(self, child_meta: PlanMeta) -> bool:
        """A chain member the fuser can ingest: Project/Filter, fully
        TPU-supported, and not a cache boundary (materialized batches
        must be consumed — and populated — there)."""
        if not isinstance(child_meta.wrapped, (L.Project, L.Filter)):
            return False
        if child_meta.reasons or any(
                not em.can_replace for em in child_meta.expr_metas):
            return False
        if self.cache_manager is not None and \
                self.cache_manager.lookup(child_meta.wrapped) is not None:
            return False
        return True

    def _try_fuse_chain(self, meta: PlanMeta):
        """Whole-stage chain fusion: collapse a maximal Project/Filter
        run into ONE FusedStageExec — projections substitute through,
        predicates AND into a single in-trace row mask, one compaction
        at the stage boundary, one jit dispatch per batch
        (exec/fusion.py).  Chains the fuser cannot ingest (UDF-only
        projections, CPU-fallback expressions, cached members) stop the
        walk and run unfused."""
        from spark_rapids_tpu.exec.fusion import (FusedStageExec,
                                                  compose_chain,
                                                  fusion_metrics)
        if id(meta.wrapped) in self._counted_chain_nodes:
            return None  # inner member of an already-detected chain
        exprs = None
        conds: List = []
        cur = meta
        members: List[str] = []
        node_ids: List[int] = []
        while self._fusible_member(cur) and \
                len(members) < self.fusion_max_ops:
            exprs, conds = compose_chain(exprs, conds, cur.wrapped,
                                         cur.wrapped.schema)
            members.append(type(cur.wrapped).__name__)
            node_ids.append(id(cur.wrapped))
            cur = cur.child_metas[0]
        if len(members) < 2:
            return None  # a lone operator is already one stage
        self._counted_chain_nodes.update(node_ids)
        self.last_fusion["fusibleChains"] += 1
        fusion_metrics.bump("fusibleChains")
        if not self.fusion_enabled:
            return None
        base = self._convert(cur)
        self.last_fusion["fusedStages"] += 1
        self.last_fusion["fusedOperators"] += len(members)
        fusion_metrics.bump("fusedStages")
        fusion_metrics.bump("fusedOperators", len(members))
        from spark_rapids_tpu.config import rapids_conf as rc
        return FusedStageExec(
            exprs, conds, base, members,
            donate=self.conf.get(rc.PIPELINE_DONATION))

    def _try_fuse_aggregate(self, meta: PlanMeta):
        """Whole-stage fusion: collapse Project/Filter chains under an
        Aggregate into the aggregation kernel (predicate becomes a row mask,
        projections compose into key/agg expressions).  The reference gets
        partial fusion from cudf kernel launches per op; XLA gives us the
        fully fused stage if we hand it one computation.
        """
        from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
        from spark_rapids_tpu.exec.fusion import fusion_metrics
        from spark_rapids_tpu.ops.expressions import substitute_bound

        node: L.Aggregate = meta.wrapped
        group = list(node.group_exprs)
        aggs = list(node.agg_exprs)
        # bottom-first conjunct list (the aggregate's _pre_filter_mask
        # applies progressive ANSI-check masking, exec/fusion.py)
        conds: List = []
        child_meta = meta.child_metas[0]
        hops = 0
        node_ids: List[int] = []
        while self._fusible_member(child_meta) and \
                hops < self.fusion_max_ops:
            inner = child_meta.wrapped
            if isinstance(inner, L.Project):
                repl = inner.exprs
                group = [substitute_bound(e, repl) for e in group]
                aggs = [substitute_bound(e, repl) for e in aggs]
                conds = [substitute_bound(c, repl) for c in conds]
            else:
                conds = [inner.condition] + conds
            node_ids.append(id(inner))
            child_meta = child_meta.child_metas[0]
            hops += 1
        if hops == 0:
            return None  # nothing upstream to fuse
        enc_exec = _encoding_exec_enabled(self.conf)
        if any(e.dtype.is_string for e in group):
            # string keys fuse ONLY under encoded execution, and only
            # when the exec's faithfulness test passes (bare refs, key
            # columns consumed nowhere else, no string agg buffers) —
            # otherwise the host dict-encode path runs unfused
            if not (enc_exec and _agg_fold_encodable(group, aggs,
                                                     conds)):
                return None
        elif conds and any(
                c.dtype.is_string for c in _agg_kernel_children(aggs)):
            # string-valued min/max buffers run the two-stage string
            # path, which cannot carry a fused predicate: leave the
            # chain unfused (the predicate compacts before the agg)
            return None
        from spark_rapids_tpu.exec.fusion import has_check_exprs
        if has_check_exprs(group + aggs + conds):
            # the aggregation kernels have no ANSI check-flag channel:
            # the chain fuses as a FusedStageExec below instead
            return None
        self.last_fusion["fusibleChains"] += 1
        fusion_metrics.bump("fusibleChains")
        if not self.fusion_enabled:
            # A/B baseline: count the lost fusion (health check) and
            # keep the chain members from re-counting as their own
            # chain during normal conversion
            self._counted_chain_nodes.update(node_ids)
            return None
        self.last_fusion["fusedStages"] += 1
        self.last_fusion["fusedOperators"] += hops + 1
        fusion_metrics.bump("fusedStages")
        fusion_metrics.bump("fusedOperators", hops + 1)
        from spark_rapids_tpu.config import rapids_conf as rc
        base = self._convert(child_meta)
        fused = _plan_aggregate(
            group, aggs, base, pre_filter=conds or None,
            merge_chunk_rows=self.conf.get(rc.AGG_MERGE_CHUNK_ROWS),
            defer_syncs=self.conf.get(rc.PIPELINE_DEFER_SYNCS),
            encoded_exec=enc_exec,
            max_dict_size=self.conf.get(rc.ENCODING_EXECUTION_MAX_DICT))
        # runtime dispatch-savings attribution (QueryEnd fusion dict):
        # each folded operator would have cost one dispatch per batch
        agg_exec = fused if isinstance(fused, TpuHashAggregateExec) \
            else fused.children[0]
        if isinstance(agg_exec, TpuHashAggregateExec):
            agg_exec.fused_ops = hops
        return fused


def valid_op_names():
    """Known per-op conf suffixes: expression class names + plan node
    names (consumed by RapidsConf's unknown-key validation)."""
    exprs = {c.__name__ for c in _EXPR_RULES}
    execs = {c.__name__ for c in _PLAN_CONVERTERS}
    # logical node names double as exec keys (Sort, Join, ...)
    return exprs | execs | {"WindowExpression"}
