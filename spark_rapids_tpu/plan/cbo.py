"""Cost-based optimizer: force subtrees back to CPU when the device is
not worth the transitions.

Counterpart of ``CostBasedOptimizer.scala:35-63`` (optional, default off
via ``spark.rapids.sql.optimizer.enabled`` — RapidsConf.scala:1177): the
reference walks the tagged meta tree with CPU/GPU cost models plus
row/columnar transition costs and reverts subtrees whose acceleration
cannot pay for the boundary crossings.

The TPU formulation works on DEVICE REGIONS: maximal connected subtrees
of can-replace nodes.  Each region's cost is

    tpu = sum(rows_i * w_tpu(op_i)) + (rows_in + rows_out) * w_transition
    cpu = sum(rows_i * w_cpu(op_i))

with rows estimated bottom-up (known for in-memory relations, heuristic
selectivities elsewhere — the reference hardcodes comparable defaults).
When ``tpu > cpu`` every node in the region is tagged
"not worth the transition cost (CBO)" and the planner's normal fallback
machinery does the rest.  A region whose BOUNDARIES are the plan's own
source/sink (scan feeds it, collect drains it) pays only the sink
transition — device-resident sources are free.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from spark_rapids_tpu.plan import logical as L

# per-row work coefficients (arbitrary units; only ratios matter)
_CPU_W = {
    "Project": 1.0, "Filter": 1.0, "Aggregate": 4.0, "Join": 6.0,
    "Sort": 5.0, "Window": 8.0, "Generate": 2.0, "Limit": 0.1,
    "Union": 0.1, "default": 1.0,
}
# the TPU runs the columnar kernels far faster but pays a fixed per-batch
# dispatch; the ratio vs _CPU_W encodes the measured ~5-8x engine speedup
_TPU_W = {k: v / 6.0 for k, v in _CPU_W.items()}


def _estimate_rows(node, child_rows: List[float]) -> float:
    if isinstance(node, L.InMemoryRelation):
        return float(sum(b.nrows for b in node.batches))
    if isinstance(node, L.FileRelation):
        return 1_000_000.0 * max(len(node.paths), 1)
    if isinstance(node, L.Range):
        step = node.step or 1
        return float(max((node.end - node.start) // step, 0))
    inp = child_rows[0] if child_rows else 0.0
    if isinstance(node, L.Filter):
        return inp * 0.5
    if isinstance(node, L.Aggregate):
        return max(inp * 0.1, 1.0)
    if isinstance(node, L.Join):
        right = child_rows[1] if len(child_rows) > 1 else 0.0
        return max(inp, right)
    if isinstance(node, L.Generate):
        return inp * 4.0
    if isinstance(node, L.Limit):
        return min(inp, float(node.n))
    if isinstance(node, L.Union):
        return float(sum(child_rows))
    return inp


class CostBasedOptimizer:
    """optimize(meta) mutates the tagged meta tree in place."""

    def __init__(self, conf):
        from spark_rapids_tpu.config import rapids_conf as rc
        self.transition_w = conf.get(rc.OPTIMIZER_TRANSITION_COST)
        self.explain: List[str] = []

    def optimize(self, meta) -> None:
        self._rows: Dict[int, float] = {}
        self._fill_rows(meta)
        self._visit_regions(meta, parent_on_tpu=False)

    def _fill_rows(self, meta) -> float:
        child_rows = [self._fill_rows(c) for c in meta.child_metas]
        rows = _estimate_rows(meta.wrapped, child_rows)
        self._rows[id(meta)] = rows
        return rows

    def _op_name(self, meta) -> str:
        return type(meta.wrapped).__name__

    @staticmethod
    def _own_ok(meta) -> bool:
        """This NODE converts to a device operator (regions are built
        from per-node viability, NOT the subtree-recursive can_replace:
        a device region legitimately sits above a CPU-fallback child and
        must still be cost-evaluated)."""
        return not meta.reasons

    def _region_cost(self, meta) -> Tuple[float, float, float, List]:
        """(tpu_work, cpu_work, rows_in_from_cpu, nodes) over the
        device region rooted at meta."""
        rows = self._rows[id(meta)]
        w = self._op_name(meta)
        tpu = rows * _TPU_W.get(w, _TPU_W["default"])
        cpu = rows * _CPU_W.get(w, _CPU_W["default"])
        rows_in = 0.0
        nodes = [meta]
        for c in meta.child_metas:
            if isinstance(c.wrapped, (L.InMemoryRelation,
                                      L.FileRelation, L.Range)):
                # leaf relations stay as-is: they source data from the
                # host either way (no transition, never reverted)
                continue
            if self._own_ok(c):
                t, p, ri, ns = self._region_cost(c)
                tpu += t
                cpu += p
                rows_in += ri
                nodes.extend(ns)
            else:
                # a CPU child feeds this region: entry transition
                rows_in += self._rows[id(c)]
        return tpu, cpu, rows_in, nodes

    def _visit_regions(self, meta, parent_on_tpu: bool) -> None:
        if isinstance(meta.wrapped, (L.InMemoryRelation, L.FileRelation,
                                     L.Range)):
            return
        if self._own_ok(meta) and not parent_on_tpu:
            tpu, cpu, rows_in, nodes = self._region_cost(meta)
            rows_out = self._rows[id(meta)]
            # the region's output always crosses to the host (collect or
            # a CPU parent)
            transitions = (rows_in + rows_out) * self.transition_w
            if tpu + transitions > cpu:
                for n in nodes:
                    n.will_not_work(
                        "not worth the transition cost "
                        f"(CBO: tpu={tpu + transitions:.0f} > "
                        f"cpu={cpu:.0f})")
                self.explain.append(
                    f"CBO reverted {self._op_name(meta)} region "
                    f"({len(nodes)} ops) to CPU")
                for c in meta.child_metas:
                    self._visit_regions(c, False)
                return
            for c in meta.child_metas:
                self._visit_regions(c, True)
            return
        for c in meta.child_metas:
            self._visit_regions(c, self._own_ok(meta) and parent_on_tpu)
