"""Cost-based optimizer: force subtrees back to CPU when the device is
not worth the transitions.

Counterpart of ``CostBasedOptimizer.scala:35-63`` (optional, default off
via ``spark.rapids.sql.optimizer.enabled`` — RapidsConf.scala:1177): the
reference walks the tagged meta tree with CPU/GPU cost models plus
row/columnar transition costs and reverts subtrees whose acceleration
cannot pay for the boundary crossings.

The TPU formulation works on DEVICE REGIONS: maximal connected subtrees
of can-replace nodes.  Each region's cost is

    tpu = sum(rows_i * w_tpu(op_i)) + (rows_in + rows_out) * w_transition
    cpu = sum(rows_i * w_cpu(op_i))

with rows estimated bottom-up (known for in-memory relations, heuristic
selectivities elsewhere — the reference hardcodes comparable defaults).
When ``tpu > cpu`` every node in the region is tagged
"not worth the transition cost (CBO)" and the planner's normal fallback
machinery does the rest.  A region whose BOUNDARIES are the plan's own
source/sink (scan feeds it, collect drains it) pays only the sink
transition — device-resident sources are free.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu.plan import logical as L

# fallback coefficients when no calibration file is present (arbitrary
# units; only ratios matter)
_BUILTIN_CPU_W = {
    "Project": 1.0, "Filter": 1.0, "Aggregate": 4.0, "Join": 6.0,
    "Sort": 5.0, "Window": 8.0, "Generate": 2.0, "Limit": 0.1,
    "Union": 0.1, "default": 1.0,
}
_BUILTIN_TPU_W = {k: v / 6.0 for k, v in _BUILTIN_CPU_W.items()}

_WEIGHTS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "cbo_weights.json")
_loaded: Optional[Tuple[Dict[str, float], Dict[str, float]]] = None
_calibrated: bool = False


def weights_calibrated() -> bool:
    """True when load_weights() served a calibration MEASURED on this
    backend; False when it fell back to the built-in ratio table
    (missing/corrupt file or platform-mismatch provenance)."""
    load_weights()
    return _calibrated


def load_weights() -> Tuple[Dict[str, float], Dict[str, float]]:
    """(tpu_w, cpu_w) in us/row from ``cbo_weights.json`` — MEASURED on
    the build machine by ``tools/cbo_calibrate.py`` (re-run it on the
    target device to recalibrate) — falling back to the built-in ratio
    table when the file is absent."""
    global _loaded, _calibrated
    if _loaded is not None:
        return _loaded
    try:
        with open(_WEIGHTS_PATH, encoding="utf-8") as f:
            blob = json.load(f)
        data = blob["weights"]
        # a calibration from a DIFFERENT backend is fiction for this
        # one (CPU-measured sort/join costs would revert every device
        # region on a real TPU): fall back to the neutral table and
        # let the operator re-run spark-rapids-tpu-cbo-calibrate
        import jax
        measured_on = blob.get("provenance", {}).get("platform")
        if measured_on is not None and \
                measured_on != jax.devices()[0].platform:
            raise ValueError(
                f"cbo_weights.json calibrated on {measured_on!r}, "
                f"running on {jax.devices()[0].platform!r}")
        tpu = {k: float(v["tpu"]) for k, v in data.items()}
        cpu = {k: float(v["cpu"]) for k, v in data.items()}
        # unmeasured ops inherit the measured median ratio
        ratios = [tpu[k] / cpu[k] for k in tpu if cpu[k] > 0]
        ratios.sort()
        med = ratios[len(ratios) // 2] if ratios else 1.0
        for k, v in _BUILTIN_CPU_W.items():
            cpu.setdefault(k, v * 0.05)   # us/row scale of the table
            tpu.setdefault(k, cpu[k] * med)
        _loaded = (tpu, cpu)
        _calibrated = True
    except (OSError, KeyError, TypeError, ValueError,
            json.JSONDecodeError):
        _calibrated = False
        # scale the unit table into the same us/row domain the
        # calibrated file (and transitionRowCost default) live in
        _loaded = ({k: v * 0.05 for k, v in _BUILTIN_TPU_W.items()},
                   {k: v * 0.05 for k, v in _BUILTIN_CPU_W.items()})
    return _loaded


def _estimate_rows(node, child_rows: List[float]) -> float:
    if isinstance(node, L.InMemoryRelation):
        return float(sum(b.nrows for b in node.batches))
    if isinstance(node, L.FileRelation):
        return 1_000_000.0 * max(len(node.paths), 1)
    if isinstance(node, L.Range):
        step = node.step or 1
        return float(max((node.end - node.start) // step, 0))
    inp = child_rows[0] if child_rows else 0.0
    if isinstance(node, L.Filter):
        return inp * 0.5
    if isinstance(node, L.Aggregate):
        return max(inp * 0.1, 1.0)
    if isinstance(node, L.Join):
        right = child_rows[1] if len(child_rows) > 1 else 0.0
        return max(inp, right)
    if isinstance(node, L.Generate):
        return inp * 4.0
    if isinstance(node, L.Limit):
        return min(inp, float(node.n))
    if isinstance(node, L.Union):
        return float(sum(child_rows))
    return inp


class CostBasedOptimizer:
    """optimize(meta) mutates the tagged meta tree in place."""

    def __init__(self, conf):
        from spark_rapids_tpu.config import rapids_conf as rc
        self.transition_w = conf.get(rc.OPTIMIZER_TRANSITION_COST)
        tpu_w, cpu_w = load_weights()
        self.tpu_w = dict(tpu_w)
        self.cpu_w = dict(cpu_w)
        # self-tuning cost model: MEASURED per-op device weights from
        # the observation store (the ``op:<Name>`` evidence records
        # the QueryEnd metric fold writes) beat the static calibration
        # file — the calibration stays the cold-start fallback, conf
        # keys below stay the final override
        from spark_rapids_tpu.plan.costmodel import model_for_conf
        cm = model_for_conf(conf)
        if cm is not None:
            for name, us in cm.op_weights().items():
                if name in self.tpu_w:
                    self.tpu_w[name] = us
        # conf keys override calibrated values per op
        for name in set(self.tpu_w) | set(self.cpu_w):
            ov = conf.op_cost("tpu", name)
            if ov is not None:
                self.tpu_w[name] = ov
            ov = conf.op_cost("cpu", name)
            if ov is not None:
                self.cpu_w[name] = ov
        self.explain: List[str] = []

    def optimize(self, meta) -> None:
        self._rows: Dict[int, float] = {}
        self._fill_rows(meta)
        self._visit_regions(meta, parent_on_tpu=False)

    def _fill_rows(self, meta) -> float:
        child_rows = [self._fill_rows(c) for c in meta.child_metas]
        rows = _estimate_rows(meta.wrapped, child_rows)
        self._rows[id(meta)] = rows
        return rows

    def _op_name(self, meta) -> str:
        return type(meta.wrapped).__name__

    @staticmethod
    def _own_ok(meta) -> bool:
        """This NODE converts to a device operator (regions are built
        from per-node viability, NOT the subtree-recursive can_replace:
        a device region legitimately sits above a CPU-fallback child and
        must still be cost-evaluated)."""
        return not meta.reasons

    def _region_cost(self, meta) -> Tuple[float, float, float, List]:
        """(tpu_work, cpu_work, rows_in_from_cpu, nodes) over the
        device region rooted at meta."""
        rows = self._rows[id(meta)]
        w = self._op_name(meta)
        tpu = rows * self.tpu_w.get(w, self.tpu_w["default"])
        cpu = rows * self.cpu_w.get(w, self.cpu_w["default"])
        rows_in = 0.0
        nodes = [meta]
        for c in meta.child_metas:
            if isinstance(c.wrapped, (L.InMemoryRelation,
                                      L.FileRelation, L.Range)):
                # leaf relations stay as-is: they source data from the
                # host either way (no transition, never reverted)
                continue
            if self._own_ok(c):
                t, p, ri, ns = self._region_cost(c)
                tpu += t
                cpu += p
                rows_in += ri
                nodes.extend(ns)
            else:
                # a CPU child feeds this region: entry transition
                rows_in += self._rows[id(c)]
        return tpu, cpu, rows_in, nodes

    def _visit_regions(self, meta, parent_on_tpu: bool) -> None:
        if isinstance(meta.wrapped, (L.InMemoryRelation, L.FileRelation,
                                     L.Range)):
            return
        if self._own_ok(meta) and not parent_on_tpu:
            tpu, cpu, rows_in, nodes = self._region_cost(meta)
            rows_out = self._rows[id(meta)]
            # the region's output always crosses to the host (collect or
            # a CPU parent)
            transitions = (rows_in + rows_out) * self.transition_w
            if tpu + transitions > cpu:
                for n in nodes:
                    n.will_not_work(
                        "not worth the transition cost "
                        f"(CBO: tpu={tpu + transitions:.0f} > "
                        f"cpu={cpu:.0f})")
                self.explain.append(
                    f"CBO reverted {self._op_name(meta)} region "
                    f"({len(nodes)} ops) to CPU")
                for c in meta.child_metas:
                    self._visit_regions(c, False)
                return
            for c in meta.child_metas:
                self._visit_regions(c, True)
            return
        for c in meta.child_metas:
            self._visit_regions(c, self._own_ok(meta) and parent_on_tpu)
