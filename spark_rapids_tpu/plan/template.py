"""Parameterized plan templates: literal hoisting and template fingerprints.

Real high-QPS serving traffic is one plan template re-issued with shifting
literals (the same dashboard filter per user with a different date range or
customer id).  Every literal change today produces a brand-new plan text, so
the result cache misses, the stage signatures change, and the jit/AOT tiers
re-trace.  :func:`hoist_literals` rewrites a bound logical plan so constant
literals become typed :class:`~spark_rapids_tpu.ops.expressions.ParamSlot`
leaves whose cache keys are VALUE-FREE — the stage compiler, fused-aggregate
kernels, and persistent AOT store then key on the *template*, and the literal
values travel as device-scalar arguments at dispatch (zero retrace, zero
recompile across literal churn).

Hoisting is deliberately conservative — a literal is only hoisted when the
swap provably changes neither the plan SHAPE nor any output name:

==========================  =================================================
refused literal             why (falls back to exact keying)
==========================  =================================================
null literals               validity structure differs from a value scalar
string literals             char-array shape depends on the value
decimal literals            precision/scale derive from the digits
inside an ANSI-checked op   check constants are baked into the traced program
unaliased projections       the output column NAME embeds the literal text
LIMIT / slot constants      row-count shaping is structural, not a parameter
join/sort/window positions  kernels there do not thread parameters (yet)
==========================  =================================================

Refused literals simply stay inline: their values remain part of the
template fingerprint, so correctness never depends on the refusal list —
a refusal only means less sharing.  Every refusal is recorded with a reason
so the profiling health check can explain a template tier that bought
nothing.

:func:`plan_signature` is the shared canonical identity walk: node
structure plus every expression's ``cache_key()`` (which DOES include
inline literal values).  The exact result-cache tier keys on it too,
closing the historical hazard where ``Project.describe`` showed only
output names and two plans differing in an aliased literal could alias.
"""

from __future__ import annotations

import copy
import dataclasses
import hashlib
import numbers
from typing import List, Optional, Tuple

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.expressions import (
    Alias, Expression, Literal, ParamSlot, literal_storage_value)
from spark_rapids_tpu.plan import logical as L

# refusal reasons (stable strings: they flow through eventlog -> profiling)
REFUSE_NULL = "null-literal"
REFUSE_STRING = "string-shape"
REFUSE_DECIMAL = "decimal-precision"
REFUSE_ANSI = "ansi-check-constant"
REFUSE_NAME = "unaliased-output-name"
REFUSE_LIMIT = "limit-shape-constant"
REFUSE_POSITION = "position-not-parameterized"


def _literal_refusal(lit: Literal) -> Optional[str]:
    """Value-class refusals: literal kinds whose swap changes trace
    shape (never hoistable, regardless of position)."""
    if lit.value is None:
        return REFUSE_NULL
    if lit.dtype.is_string:
        return REFUSE_STRING
    if lit.dtype.is_decimal:
        return REFUSE_DECIMAL
    return None


def _contains_literal(e: Expression) -> bool:
    if isinstance(e, Literal):
        return True
    return any(_contains_literal(c) for c in e.children)


def check_bindable(value, dtype: DataType) -> None:
    """Reject a parameter binding that could not have been the hoisted
    literal: silent jnp coercion (a float truncating into an int slot)
    must never stand in for a type error."""
    if value is None:
        raise TypeError(
            f"cannot bind None to a {dtype.name} parameter slot (null "
            "literals are never hoisted — issue the query with the null "
            "inline)")
    if dtype.name == "boolean":
        if not isinstance(value, bool):
            raise TypeError(f"parameter expects boolean, got {value!r}")
        return
    if dtype.is_integral:
        if isinstance(value, bool) or \
                not isinstance(value, numbers.Integral):
            raise TypeError(
                f"parameter expects {dtype.name}, got {value!r}")
        return
    if dtype.is_floating:
        if isinstance(value, bool) or not isinstance(value, numbers.Real):
            raise TypeError(
                f"parameter expects {dtype.name}, got {value!r}")
        return
    # date/timestamp: accept what Literal accepts (ints or parseable
    # date-likes); literal_storage_value raises on garbage
    if dtype.is_datetime:
        literal_storage_value(value, dtype)
        return
    raise TypeError(f"{dtype.name} parameters are not hoistable")


class _Hoister:
    def __init__(self):
        self.slots: List[ParamSlot] = []
        self.refusals: List[Tuple[str, str]] = []

    # ------------------------------------------------------- expressions --
    def _hoist_expr(self, e: Expression, ansi: bool = False) -> Expression:
        if isinstance(e, Literal):
            reason = _literal_refusal(e)
            if reason is None and ansi:
                reason = REFUSE_ANSI
            if reason is not None:
                self.refusals.append((reason, str(e)))
                return e
            slot = ParamSlot(len(self.slots), e.dtype, e.value)
            self.slots.append(slot)
            return slot
        if not e.children:
            return e
        # any ANSI-checked operator (Cast ansi=True today) bakes its
        # check constants into the traced program: refuse underneath
        child_ansi = ansi or bool(getattr(e, "ansi", False))
        new = [self._hoist_expr(c, child_ansi) for c in e.children]
        if all(n is o for n, o in zip(new, e.children)):
            return e
        return e.with_children(new)

    def _hoist_named(self, e: Expression) -> Expression:
        """Output-name-exposed position (projection / aggregate lists):
        only an Alias pins the column name against the rewrite."""
        if isinstance(e, Alias):
            inner = self._hoist_expr(e.child)
            return e if inner is e.child else Alias(inner, e.alias)
        if _contains_literal(e):
            self.refusals.append((REFUSE_NAME, e.name))
        return e

    # ------------------------------------------------------------- nodes --
    def visit(self, plan: L.LogicalPlan) -> L.LogicalPlan:
        new_children = [self.visit(c) for c in plan.children]
        changed = any(n is not o
                      for n, o in zip(new_children, plan.children))
        fields = {}
        if isinstance(plan, L.Filter):
            cond = self._hoist_expr(plan.condition)
            if cond is not plan.condition:
                fields["condition"] = cond
        elif isinstance(plan, L.Project):
            exprs = [self._hoist_named(e) for e in plan.exprs]
            if any(n is not o for n, o in zip(exprs, plan.exprs)):
                fields["exprs"] = exprs
        elif isinstance(plan, L.Aggregate):
            group = [self._hoist_named(e) for e in plan.group_exprs]
            aggs = [self._hoist_named(e) for e in plan.agg_exprs]
            if any(n is not o for n, o in zip(group, plan.group_exprs)):
                fields["group_exprs"] = group
            if any(n is not o for n, o in zip(aggs, plan.agg_exprs)):
                fields["agg_exprs"] = aggs
        elif isinstance(plan, L.Limit):
            self.refusals.append((REFUSE_LIMIT, f"LIMIT {plan.n}"))
        else:
            # out-of-scope expression positions (join keys/conditions,
            # sort orders, windows, ...): literals stay inline — record
            # one refusal per node so churn there is explainable
            if any(_contains_literal(e) for e in _all_expressions(plan)):
                self.refusals.append((REFUSE_POSITION, plan.node_name()))
        if not changed and not fields:
            return plan
        node = copy.copy(plan)  # NEVER deepcopy: relations hold live batches
        node.children = tuple(new_children)
        for k, v in fields.items():
            setattr(node, k, v)
        return node


def _all_expressions(node: L.LogicalPlan) -> List[Expression]:
    from spark_rapids_tpu.plan.overrides import _node_expressions
    exprs = list(_node_expressions(node))
    cond = getattr(node, "condition", None)
    if isinstance(node, L.Join) and cond is not None:
        exprs.append(cond)
    return exprs


def plan_signature(plan: L.LogicalPlan) -> Tuple:
    """Canonical structural identity: node names/describe lines plus
    every expression's cache_key (inline literal VALUES included,
    ParamSlot keys value-free).  This — not the rendered tree text —
    is what cache tiers key on."""
    recs: List[Tuple] = []

    def rec(node: L.LogicalPlan, depth: int) -> None:
        entry: List = [depth, node.node_name(), node.describe()]
        exprs = _all_expressions(node)
        if exprs:
            entry.append(tuple(e.cache_key() for e in exprs))
        if isinstance(node, L.Limit):
            entry.append(("n", node.n))
        if isinstance(node, L.FileRelation):
            entry.append(("paths", tuple(node.paths), node.file_format))
        recs.append(tuple(entry))
        for c in node.children:
            rec(c, depth + 1)

    rec(plan, 0)
    return tuple(recs)


def plan_fingerprint(plan: L.LogicalPlan) -> str:
    return hashlib.sha256(
        repr(plan_signature(plan)).encode("utf-8")).hexdigest()


@dataclasses.dataclass
class TemplateInfo:
    """A hoisted plan template plus its current parameter binding.

    ``plan`` shares every un-rewritten subtree with the original logical
    plan (relations, joins, ... are the same objects); only nodes along
    a rewritten expression path are shallow-copied.  The ParamSlots are
    OWNED by this template — binding new values mutates them, so one
    TemplateInfo must not execute concurrently with itself (the
    prepared-statement handle serializes runs; the ad-hoc path hoists a
    fresh template per query).
    """

    plan: L.LogicalPlan
    slots: List[ParamSlot]
    refusals: List[Tuple[str, str]]
    fingerprint: str

    @property
    def hoisted(self) -> bool:
        return bool(self.slots)

    @property
    def param_count(self) -> int:
        return len(self.slots)

    def bind(self, values) -> None:
        """Bind a positional parameter vector (type-checked)."""
        if len(values) != len(self.slots):
            raise ValueError(
                f"template expects {len(self.slots)} parameters, "
                f"got {len(values)}")
        for s, v in zip(self.slots, values):
            check_bindable(v, s.dtype)
        for s, v in zip(self.slots, values):
            s.bind_value(v)

    def values(self) -> Tuple:
        return tuple(s.value for s in self.slots)

    def param_vector(self) -> Tuple:
        """Canonical (dtype, storage-value) vector of the CURRENT
        binding — the template result-cache key component."""
        return tuple(
            (s.dtype.name,
             repr(literal_storage_value(s.value, s.dtype)))
            for s in self.slots)


def hoist_literals(plan: L.LogicalPlan) -> TemplateInfo:
    """Rewrite ``plan`` into its parameterized template.

    Returns a TemplateInfo whose slots carry the original literal values
    as their initial binding, so ``info.plan`` executes identically to
    ``plan`` without further binding.  ``info.hoisted`` is False when
    nothing was hoistable — callers then stay on the exact-key path.
    """
    h = _Hoister()
    tplan = h.visit(plan)
    return TemplateInfo(plan=tplan, slots=h.slots, refusals=h.refusals,
                        fingerprint=plan_fingerprint(tplan))
