"""Logical plan nodes.

The reference plugs into Spark's Catalyst and never owns a logical plan; this
framework is standalone, so it carries a small Catalyst-shaped logical algebra
that the DataFrame API builds and ``plan/overrides.py`` lowers to TpuExec
physical operators (the GpuOverrides analog, GpuOverrides.scala:3258).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.dtypes import INT32, DataType
from spark_rapids_tpu.ops.aggregates import AggregateFunction
from spark_rapids_tpu.ops.expressions import (
    Alias, ColVal, EmitContext, Expression,
)

Schema = List[Tuple[str, DataType]]


class AggregateExpression(Expression):
    """Expression wrapper around an AggregateFunction (mirrors Catalyst's)."""

    def __init__(self, func: AggregateFunction):
        self.func = func
        self.children = (func.child,) if func.child is not None else ()

    def with_children(self, children):
        import copy
        f = copy.copy(self.func)
        f.child = children[0] if children else None
        return AggregateExpression(f)

    def bind(self, schema):
        return self.with_children([c.bind(schema) for c in self.children])

    @property
    def dtype(self) -> DataType:
        return self.func.result_dtype

    @property
    def nullable(self) -> bool:
        return self.func.result_nullable

    @property
    def name(self) -> str:
        arg = self.func.child.name if self.func.child is not None else "*"
        return f"{self.func.name}({arg})"

    def emit(self, ctx: EmitContext) -> ColVal:
        raise RuntimeError(
            "AggregateExpression must be planned by TpuHashAggregateExec, "
            "not emitted directly")

    def cache_key(self):
        return ("AggregateExpression", self.func.cache_key())

    def __str__(self):
        return self.name


class LogicalPlan:
    children: Tuple["LogicalPlan", ...] = ()

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    def node_name(self) -> str:
        return type(self).__name__

    def __str__(self) -> str:
        lines: List[str] = []

        def rec(node, depth):
            lines.append("  " * depth + node.describe())
            for c in node.children:
                rec(c, depth + 1)
        rec(self, 0)
        return "\n".join(lines)

    def describe(self) -> str:
        return self.node_name()

    def tree_string(self) -> str:
        from spark_rapids_tpu.utils.trees import render_tree
        return render_tree(self)


class InMemoryRelation(LogicalPlan):
    def __init__(self, batches: Sequence[ColumnarBatch], schema: Schema):
        self.batches = list(batches)
        self._schema = list(schema)

    @property
    def schema(self) -> Schema:
        return self._schema

    def describe(self):
        rows = sum(b.nrows for b in self.batches)
        return f"InMemoryRelation[{rows} rows]"


class FileRelation(LogicalPlan):
    # the per-file metadata columns the scan can expose on request
    # (GpuFileSourceScanExec metadata-column analog): input_file_name()
    # and the _metadata struct (shredded — see columnar/nested.py)
    INPUT_FILE_COL = "__input_file_name"

    def __init__(self, paths: Sequence[str], file_format: str, schema: Schema,
                 options: Optional[dict] = None, bucket_spec=None):
        self.paths = list(paths)
        self.file_format = file_format
        self._schema = list(schema)
        self.options = dict(options or {})
        # set by the planner's pushdown pass (GpuParquetScan predicate
        # pushdown + column pruning analog)
        self.pushed_filters: List[Expression] = []
        self.required_columns = None  # None = all
        # subset of {"input_file", "metadata"}; set by the DataFrame
        # layer when a query references the metadata columns
        self.file_meta = set()
        # {"column", "num_buckets"} from the _bucket_spec.json sidecar
        self.bucket_spec = bucket_spec

    @property
    def schema(self) -> Schema:
        from spark_rapids_tpu.columnar.dtypes import (
            INT64, STRING, TIMESTAMP_US)
        out = list(self._schema)
        if "input_file" in self.file_meta:
            out.append((self.INPUT_FILE_COL, STRING))
        if "metadata" in self.file_meta:
            out += [("_metadata.file_path", STRING),
                    ("_metadata.file_name", STRING),
                    ("_metadata.file_size", INT64),
                    ("_metadata.file_modification_time", TIMESTAMP_US)]
        return out

    def describe(self):
        extra = ", bucketed" if self.bucket_spec else ""
        return (f"FileRelation[{self.file_format}, {len(self.paths)} "
                f"files{extra}]")


class Project(LogicalPlan):
    def __init__(self, exprs: Sequence[Expression], child: LogicalPlan):
        self.exprs = [e.bind(child.schema) for e in exprs]
        self.children = (child,)

    @property
    def child(self):
        return self.children[0]

    @property
    def schema(self) -> Schema:
        return [(e.name, e.dtype) for e in self.exprs]

    def describe(self):
        return f"Project[{', '.join(e.name for e in self.exprs)}]"


class Filter(LogicalPlan):
    def __init__(self, condition: Expression, child: LogicalPlan):
        self.condition = condition.bind(child.schema)
        self.children = (child,)

    @property
    def child(self):
        return self.children[0]

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def describe(self):
        return f"Filter[{self.condition}]"


class Aggregate(LogicalPlan):
    """group_exprs may be empty (grand-total reduction)."""

    def __init__(self, group_exprs: Sequence[Expression],
                 agg_exprs: Sequence[Expression], child: LogicalPlan):
        self.group_exprs = [e.bind(child.schema) for e in group_exprs]
        self.agg_exprs = [e.bind(child.schema) for e in agg_exprs]
        self.children = (child,)

    @property
    def child(self):
        return self.children[0]

    @property
    def schema(self) -> Schema:
        out = [(e.name, e.dtype) for e in self.group_exprs]
        out += [(e.name, e.dtype) for e in self.agg_exprs]
        return out

    def describe(self):
        return (f"Aggregate[keys={[e.name for e in self.group_exprs]}, "
                f"aggs={[e.name for e in self.agg_exprs]}]")


class Join(LogicalPlan):
    def __init__(self, left: LogicalPlan, right: LogicalPlan,
                 left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression], join_type: str,
                 condition: Optional[Expression] = None,
                 using: Optional[Sequence[str]] = None):
        self.left_keys = [e.bind(left.schema) for e in left_keys]
        self.right_keys = [e.bind(right.schema) for e in right_keys]
        self.join_type = join_type
        self.using = list(using) if using else None
        self.children = (left, right)
        # residual (non-equi) condition binds against left+right columns
        # (NOT Join.schema: semi/anti schemas drop the right side but a
        # residual may legitimately reference it — the planner then tags
        # the join off gracefully instead of a bind KeyError)
        self.condition = condition.bind(
            list(left.schema) + list(right.schema)) \
            if condition is not None else None

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]

    @property
    def schema(self) -> Schema:
        left = self.left.schema
        right = self.right.schema
        if self.join_type in ("semi", "anti"):
            return list(left)
        if self.using:
            keyset = set(self.using)
            out = [(n, dt) for n, dt in left if n in keyset]
            out += [(n, dt) for n, dt in left if n not in keyset]
            out += [(n, dt) for n, dt in right if n not in keyset]
            return out
        return list(left) + list(right)

    def describe(self):
        keys = list(zip([e.name for e in self.left_keys],
                        [e.name for e in self.right_keys]))
        return f"Join[{self.join_type}, on={keys}]"


class AggInPandas(LogicalPlan):
    """groupBy().agg(grouped-agg pandas UDFs)."""

    def __init__(self, group_names: Sequence[str], aggs: Sequence[tuple],
                 child: LogicalPlan):
        self.group_names = list(group_names)
        self.aggs = list(aggs)  # (name, fn, arg_name, dtype)
        self.children = (child,)

    @property
    def child(self):
        return self.children[0]

    @property
    def schema(self) -> Schema:
        child_schema = dict(self.child.schema)
        out = [(n, child_schema[n]) for n in self.group_names]
        out += [(name, dt) for name, _, _, dt in self.aggs]
        return out

    def describe(self):
        return f"AggInPandas[{[n for n, *_ in self.aggs]}]"


class WindowInPandas(LogicalPlan):
    """Pandas UDFs evaluated over window frames
    (GpuWindowInPandasExec analog, python/GpuWindowInPandasExec.scala).
    Output = child columns + one column per windowed UDF."""

    def __init__(self, calls: Sequence[tuple], child: LogicalPlan):
        # calls: (out_name, fn, arg_name, dtype,
        #         (partition_names, orders, frame))
        self.calls = list(calls)
        self.children = (child,)
        child_names = {n for n, _ in child.schema}
        for out_name, _, arg, _, (parts, orders, _) in self.calls:
            if out_name in child_names:
                raise ValueError(
                    f"windowed pandas UDF output {out_name!r} collides "
                    "with a child column (the select() router assigns "
                    "internal names — construct through it)")
            for c in [arg] + list(parts) + [n for n, _, _ in orders]:
                if c not in child_names:
                    raise KeyError(
                        f"windowed pandas UDF references unknown "
                        f"column {c!r}")

    @property
    def child(self):
        return self.children[0]

    @property
    def schema(self) -> Schema:
        return list(self.child.schema) + \
            [(name, dt) for name, _, _, dt, _ in self.calls]

    def describe(self):
        return f"WindowInPandas[{[n for n, *_ in self.calls]}]"


class CoGroupMapInPandas(LogicalPlan):
    """cogroup().applyInPandas."""

    def __init__(self, fn, out_schema: Schema, left_names, right_names,
                 left: LogicalPlan, right: LogicalPlan):
        self.fn = fn
        self._schema = list(out_schema)
        self.left_names = list(left_names)
        self.right_names = list(right_names)
        self.children = (left, right)

    @property
    def schema(self) -> Schema:
        return self._schema

    def describe(self):
        return "CoGroupMapInPandas"


class BatchId(LogicalPlan):
    """Appends the per-batch id columns consumed by
    monotonically_increasing_id()/spark_partition_id()."""

    def __init__(self, child: LogicalPlan):
        self.children = (child,)

    @property
    def child(self):
        return self.children[0]

    @property
    def schema(self) -> Schema:
        from spark_rapids_tpu.columnar.dtypes import INT64
        return list(self.child.schema) + [("__mid", INT64),
                                          ("__pid", INT32)]

    def describe(self):
        return "BatchId"


class Sort(LogicalPlan):
    def __init__(self, orders: Sequence[Tuple[Expression, bool, bool]],
                 child: LogicalPlan):
        """orders: (expr, descending, nulls_first)"""
        self.orders = [(e.bind(child.schema), d, nf) for e, d, nf in orders]
        self.children = (child,)

    @property
    def child(self):
        return self.children[0]

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def describe(self):
        parts = [f"{e.name} {'DESC' if d else 'ASC'}"
                 for e, d, _ in self.orders]
        return f"Sort[{', '.join(parts)}]"


class Limit(LogicalPlan):
    def __init__(self, n: int, child: LogicalPlan):
        self.n = int(n)
        self.children = (child,)

    @property
    def child(self):
        return self.children[0]

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def describe(self):
        return f"Limit[{self.n}]"


class Union(LogicalPlan):
    def __init__(self, children: Sequence[LogicalPlan]):
        self.children = tuple(children)
        first = self.children[0].schema
        for c in self.children[1:]:
            if [dt.name for _, dt in c.schema] != [dt.name for _, dt in first]:
                raise ValueError("union children schemas differ")

    @property
    def schema(self) -> Schema:
        return self.children[0].schema


class MapInPandas(LogicalPlan):
    """df.mapInPandas / groupBy().applyInPandas host-function nodes."""

    def __init__(self, fn, out_schema: Schema, child: LogicalPlan,
                 group_names: Optional[Sequence[str]] = None):
        self.fn = fn
        self._schema = list(out_schema)
        self.group_names = list(group_names) if group_names else None
        self.children = (child,)

    @property
    def child(self):
        return self.children[0]

    @property
    def schema(self) -> Schema:
        return self._schema

    def describe(self):
        kind = "FlatMapGroupsInPandas" if self.group_names else "MapInPandas"
        return f"{kind}[{getattr(self.fn, '__name__', 'fn')}]"


class Generate(LogicalPlan):
    """explode/posexplode of one array-typed generator over the child
    (GpuGenerateExec.scala analog).  ``required`` are pass-through child
    expressions repeated per output element."""

    def __init__(self, generator: Expression, required, position: bool,
                 child: LogicalPlan, col_name: str = "col",
                 pos_name: str = "pos"):
        from spark_rapids_tpu.columnar.nested import (
            MAP_KEY_SUFFIX, MAP_VALUE_SUFFIX, is_shredded_map)
        from spark_rapids_tpu.ops.expressions import UnresolvedColumn
        names = [n for n, _ in child.schema]
        # explode(map) emits key+value columns (Spark's map explode):
        # the shredded arrays share offsets, so both ride one row
        # expansion
        self.map_mode = (
            isinstance(generator, UnresolvedColumn)
            and is_shredded_map(generator.col_name, names))
        if self.map_mode:
            base = generator.col_name
            self.generator = UnresolvedColumn(
                base + MAP_KEY_SUFFIX).bind(child.schema)
            self.generator2 = UnresolvedColumn(
                base + MAP_VALUE_SUFFIX).bind(child.schema)
        else:
            self.generator = generator.bind(child.schema)
            self.generator2 = None
        self.required = [e.bind(child.schema) for e in required]
        self.position = position
        self.col_name = col_name
        self.pos_name = pos_name
        taken = {e.name for e in self.required}
        clash = {"key", "value"} if self.map_mode else {col_name}
        if position:
            clash |= {pos_name}
        if taken & clash:
            raise ValueError(
                f"explode output name(s) {sorted(taken & clash)} collide "
                "with pass-through columns; alias the explode (e.g. "
                ".alias('elem'))")
        self.children = (child,)

    @property
    def child(self):
        return self.children[0]

    @property
    def schema(self) -> Schema:
        out = [(e.name, e.dtype) for e in self.required]
        if self.position:
            out.append((self.pos_name, INT32))
        if self.map_mode:
            out.append(("key", self.generator.dtype.element))
            out.append(("value", self.generator2.dtype.element))
        else:
            out.append((self.col_name, self.generator.dtype.element))
        return out

    def describe(self):
        kind = "posexplode" if self.position else "explode"
        return f"Generate[{kind}({self.generator.name})]"


class Window(LogicalPlan):
    """Append window-function columns (WindowExec analog)."""

    def __init__(self, window_exprs: Sequence[Tuple[str, Expression]],
                 child: LogicalPlan):
        # (output name, WindowExpression) pairs, bound to child schema
        self.window_exprs = [(n, e.bind(child.schema))
                             for n, e in window_exprs]
        self.children = (child,)

    @property
    def child(self):
        return self.children[0]

    @property
    def schema(self) -> Schema:
        return list(self.child.schema) + \
            [(n, e.dtype) for n, e in self.window_exprs]

    def describe(self):
        return f"Window[{[n for n, _ in self.window_exprs]}]"


class Range(LogicalPlan):
    def __init__(self, start: int, end: int, step: int = 1):
        from spark_rapids_tpu.columnar import dtypes as dts
        self.start, self.end, self.step = start, end, step
        self._schema = [("id", dts.INT64)]

    @property
    def schema(self) -> Schema:
        return self._schema

    def describe(self):
        return f"Range[{self.start}, {self.end}, {self.step}]"
