// TPU-host native runtime: the C++ counterpart of the reference's native
// layer (RMM host/pinned pools, JCudfSerialization framing, RapidsDiskStore
// spill files, and the multithreaded-reader thread pool —
// GpuDeviceManager.scala:216, GpuColumnarBatchSerializer.scala:25,
// RapidsDiskStore, GpuParquetScan.scala:973).  The TPU compute path is
// XLA; everything here is host-side plumbing around it: staging memory,
// columnar frame (de)serialization with a zero-RLE codec, streamed spill
// file IO, and a background file prefetcher.
//
// Exposed as a flat C ABI consumed from Python via ctypes
// (spark_rapids_tpu/native/__init__.py).  No external dependencies.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <new>
#include <string>
#include <thread>
#include <vector>

#if defined(__unix__) || defined(__APPLE__)
#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>
#endif

extern "C" {

// ---------------------------------------------------------------------------
// 1. Host arena allocator (pinned-pool analog).
//
// A growable arena of large slabs with a size-bucketed free list.  Staging
// buffers for device upload/download and shuffle assembly are allocated and
// released in waves; a bump-with-recycling arena avoids malloc churn and
// fragmentation the way the reference's RMM pool does for pinned memory.
// ---------------------------------------------------------------------------

struct ArenaBlock {
    uint8_t *base;
    size_t size;
    size_t used;
};

struct Arena {
    std::mutex mu;
    std::vector<ArenaBlock> blocks;
    // free list: size -> list of (ptr, size) recycled allocations
    std::multimap<size_t, uint8_t *> free_list;
    size_t slab_bytes;
    size_t total_reserved = 0;
    size_t total_allocated = 0;  // live bytes handed out
    size_t high_watermark = 0;
};

static const size_t kAlign = 64;

static size_t align_up(size_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }

void *arena_create(size_t slab_bytes) {
    Arena *a = new (std::nothrow) Arena();
    if (!a) return nullptr;
    a->slab_bytes = slab_bytes < (1u << 20) ? (1u << 20) : slab_bytes;
    return a;
}

void *arena_alloc(void *arena, size_t nbytes) {
    Arena *a = static_cast<Arena *>(arena);
    size_t want = align_up(nbytes ? nbytes : 1);
    std::lock_guard<std::mutex> lock(a->mu);
    // exact-or-larger recycled block (first fit in size order, split never:
    // buffers cluster around repeated sizes so exact reuse dominates)
    auto it = a->free_list.lower_bound(want);
    if (it != a->free_list.end() && it->first <= want * 2) {
        uint8_t *p = it->second;
        a->free_list.erase(it);
        a->total_allocated += want;
        if (a->total_allocated > a->high_watermark)
            a->high_watermark = a->total_allocated;
        return p;
    }
    // bump from the last slab
    if (a->blocks.empty() ||
        a->blocks.back().used + want > a->blocks.back().size) {
        size_t slab = want > a->slab_bytes ? want : a->slab_bytes;
        uint8_t *base = static_cast<uint8_t *>(std::malloc(slab));
        if (!base) return nullptr;
        a->blocks.push_back({base, slab, 0});
        a->total_reserved += slab;
    }
    ArenaBlock &b = a->blocks.back();
    uint8_t *p = b.base + b.used;
    b.used += want;
    a->total_allocated += want;
    if (a->total_allocated > a->high_watermark)
        a->high_watermark = a->total_allocated;
    return p;
}

void arena_free(void *arena, void *ptr, size_t nbytes) {
    Arena *a = static_cast<Arena *>(arena);
    size_t want = align_up(nbytes ? nbytes : 1);
    std::lock_guard<std::mutex> lock(a->mu);
    a->free_list.emplace(want, static_cast<uint8_t *>(ptr));
    a->total_allocated -= want;
}

void arena_stats(void *arena, size_t *reserved, size_t *allocated,
                 size_t *watermark) {
    Arena *a = static_cast<Arena *>(arena);
    std::lock_guard<std::mutex> lock(a->mu);
    *reserved = a->total_reserved;
    *allocated = a->total_allocated;
    *watermark = a->high_watermark;
}

void arena_destroy(void *arena) {
    Arena *a = static_cast<Arena *>(arena);
    for (auto &b : a->blocks) std::free(b.base);
    delete a;
}

// ---------------------------------------------------------------------------
// 2. Columnar frame serializer (JCudfSerialization analog).
//
// Frame layout (little-endian):
//   u32 magic 'TCF1' | u32 ncols | u64 nrows
//   per column: u8 dtype_code | u8 flags (1=validity, 2=offsets)
//               u64 data_len | u64 validity_len | u64 offsets_len
//   then per column, each buffer: u8 codec (0=raw, 1=zrle)
//               u64 encoded_len | bytes
// zrle: runs of zero bytes collapse to (0x00, varint run_len); literal runs
// are (len-prefixed) copies — validity masks and null-heavy payloads are
// mostly zeros/ones, the cheap win the reference gets from nvcomp-LZ4.
// ---------------------------------------------------------------------------

static void put_u32(std::vector<uint8_t> &o, uint32_t v) {
    o.insert(o.end(), reinterpret_cast<uint8_t *>(&v),
             reinterpret_cast<uint8_t *>(&v) + 4);
}
static void put_u64(std::vector<uint8_t> &o, uint64_t v) {
    o.insert(o.end(), reinterpret_cast<uint8_t *>(&v),
             reinterpret_cast<uint8_t *>(&v) + 8);
}
static void put_varint(std::vector<uint8_t> &o, uint64_t v) {
    while (v >= 0x80) {
        o.push_back(static_cast<uint8_t>(v) | 0x80);
        v >>= 7;
    }
    o.push_back(static_cast<uint8_t>(v));
}
static uint64_t get_varint(const uint8_t *&p) {
    uint64_t v = 0;
    int shift = 0;
    while (*p & 0x80) {
        v |= static_cast<uint64_t>(*p++ & 0x7F) << shift;
        shift += 7;
    }
    v |= static_cast<uint64_t>(*p++) << shift;
    return v;
}

// bounded variant: never reads at/past `end`; returns false on truncation
static bool get_varint_bounded(const uint8_t *&p, const uint8_t *end,
                               uint64_t *out) {
    uint64_t v = 0;
    int shift = 0;
    while (p < end && (*p & 0x80)) {
        v |= static_cast<uint64_t>(*p++ & 0x7F) << shift;
        shift += 7;
        if (shift > 63) return false;
    }
    if (p >= end) return false;
    v |= static_cast<uint64_t>(*p++) << shift;
    *out = v;
    return true;
}

// zero-run-length encode; returns false (caller stores raw) when no gain
static bool zrle_encode(const uint8_t *src, size_t n,
                        std::vector<uint8_t> &out) {
    out.clear();
    out.reserve(n / 2);
    size_t i = 0;
    while (i < n) {
        if (src[i] == 0) {
            size_t run = 1;
            while (i + run < n && src[i + run] == 0) run++;
            out.push_back(0x00);
            put_varint(out, run);
            i += run;
        } else {
            size_t lit = 1;
            while (i + lit < n && src[i + lit] != 0) lit++;
            out.push_back(0x01);
            put_varint(out, lit);
            out.insert(out.end(), src + i, src + i + lit);
            i += lit;
        }
        if (out.size() >= n) return false;  // not compressing, bail
    }
    return out.size() < n;
}

// returns 0 on success, <0 on corrupt/truncated input; every run length is
// bounded against both the remaining source and the destination capacity so
// a bad spill/cache file yields an error code, not a heap overflow
static int zrle_decode(const uint8_t *src, size_t encoded_len, uint8_t *dst,
                       size_t n) {
    const uint8_t *p = src;
    const uint8_t *end = src + encoded_len;
    size_t o = 0;
    while (p < end && o < n) {
        uint8_t tag = *p++;
        uint64_t len;
        if (!get_varint_bounded(p, end, &len)) return -1;
        if (len > n - o) return -2;
        if (tag == 0x00) {
            std::memset(dst + o, 0, len);
        } else {
            if (len > static_cast<uint64_t>(end - p)) return -3;
            std::memcpy(dst + o, p, len);
            p += len;
        }
        o += len;
    }
    // a truncated stream that under-fills the destination is corrupt —
    // accepting it would hand back uninitialized tail bytes
    return o == n ? 0 : -5;
}

// ---------------------------------------------------------------------------
// lzb: LZ4-class byte compressor (greedy hash-table match finder, 64 KiB
// window; own framing, no interop needed).  The general-payload codec the
// reference gets from nvcomp-LZ4 (TableCompressionCodec.scala) — zrle stays
// the cheap win for zero-heavy validity masks, lzb catches repetitive data
// and string payloads.
//
// Stream: tokens of u8 (lit_len:4 | match_len:4); lit_len==15 extends by
// varint; literal bytes; u16 LE offset (0 = end marker, stream ends after
// the final literal run); match_len==15 extends by varint; real match
// length = match_len + 4.
// ---------------------------------------------------------------------------
static bool lzb_encode(const uint8_t *src, size_t n,
                       std::vector<uint8_t> &out) {
    out.clear();
    if (n < 16) return false;
    out.reserve(n / 2);
    const uint32_t HBITS = 13;
    // reused across calls: frame_serialize invokes this once per buffer
    // per column, and a fresh 64 KiB table per call would dominate the
    // spill/cache hot path for wide frames
    static thread_local std::vector<int64_t> head;
    head.assign(1u << HBITS, -1);
    auto hash4 = [&](uint32_t v) { return (v * 2654435761u) >> (32 - HBITS); };
    size_t i = 0, anchor = 0;
    while (i + 4 <= n) {
        uint32_t v;
        std::memcpy(&v, src + i, 4);
        uint32_t h = hash4(v);
        int64_t cand = head[h];
        head[h] = static_cast<int64_t>(i);
        if (cand >= 0 && i - cand <= 0xFFFF) {
            uint32_t cv;
            std::memcpy(&cv, src + cand, 4);
            if (cv == v) {
                size_t m = 4;
                while (i + m < n && src[cand + m] == src[i + m]) m++;
                size_t lit = i - anchor;
                size_t ml = m - 4;
                out.push_back(static_cast<uint8_t>(
                    ((lit < 15 ? lit : 15) << 4) | (ml < 15 ? ml : 15)));
                if (lit >= 15) put_varint(out, lit - 15);
                out.insert(out.end(), src + anchor, src + i);
                uint16_t off = static_cast<uint16_t>(i - cand);
                out.push_back(static_cast<uint8_t>(off & 0xFF));
                out.push_back(static_cast<uint8_t>(off >> 8));
                if (ml >= 15) put_varint(out, ml - 15);
                i += m;
                anchor = i;
                if (out.size() >= n) return false;
                continue;
            }
        }
        i++;
    }
    size_t lit = n - anchor;
    out.push_back(static_cast<uint8_t>((lit < 15 ? lit : 15) << 4));
    if (lit >= 15) put_varint(out, lit - 15);
    out.insert(out.end(), src + anchor, src + n);
    out.push_back(0);
    out.push_back(0);  // offset 0 = end marker
    return out.size() < n;
}

// 0 on success, <0 on corrupt input; all lengths/offsets bounded against
// source remainder, destination capacity, and decoded position
static int lzb_decode(const uint8_t *src, size_t encoded_len, uint8_t *dst,
                      size_t n) {
    const uint8_t *p = src;
    const uint8_t *end = src + encoded_len;
    size_t o = 0;
    while (p < end) {
        uint8_t tok = *p++;
        uint64_t lit = tok >> 4;
        if (lit == 15) {
            uint64_t ext;
            if (!get_varint_bounded(p, end, &ext)) return -1;
            lit += ext;
        }
        if (lit > n - o || lit > static_cast<uint64_t>(end - p)) return -2;
        std::memcpy(dst + o, p, lit);
        p += lit;
        o += lit;
        if (end - p < 2) return -3;
        uint16_t off = static_cast<uint16_t>(p[0] | (p[1] << 8));
        p += 2;
        if (off == 0) return o == n ? 0 : -4;  // end marker
        uint64_t ml = tok & 15;
        if (ml == 15) {
            uint64_t ext;
            if (!get_varint_bounded(p, end, &ext)) return -5;
            ml += ext;
        }
        ml += 4;
        if (off > o) return -6;
        if (ml > n - o) return -7;
        for (uint64_t j = 0; j < ml; j++, o++)  // overlap-safe byte copy
            dst[o] = dst[o - off];
    }
    return -8;  // ran out of input before the end marker
}

struct FrameBuf {
    std::vector<uint8_t> bytes;
};

// buffers: 3 per column (data, validity, offsets); null ptr + 0 len = absent
void *frame_serialize(uint64_t nrows, uint32_t ncols,
                      const uint8_t **bufs, const uint64_t *lens,
                      const uint8_t *dtype_codes, int try_compress,
                      uint64_t *out_len) {
    FrameBuf *f = new FrameBuf();
    std::vector<uint8_t> &o = f->bytes;
    put_u32(o, 0x31464354u);  // 'TCF1'
    put_u32(o, ncols);
    put_u64(o, nrows);
    for (uint32_t c = 0; c < ncols; c++) {
        uint8_t flags = 0;
        if (bufs[c * 3 + 1]) flags |= 1;
        if (bufs[c * 3 + 2]) flags |= 2;
        o.push_back(dtype_codes[c]);
        o.push_back(flags);
        put_u64(o, lens[c * 3 + 0]);
        put_u64(o, lens[c * 3 + 1]);
        put_u64(o, lens[c * 3 + 2]);
    }
    // try_compress: 0 = raw, 1 = zrle, 2 = zrle AND lzb, keep the smaller
    std::vector<uint8_t> scratch, scratch2;
    for (uint32_t c = 0; c < ncols; c++) {
        for (int k = 0; k < 3; k++) {
            const uint8_t *src = bufs[c * 3 + k];
            uint64_t n = lens[c * 3 + k];
            if (!src || n == 0) continue;
            bool z = try_compress >= 1 && n >= 64 &&
                     zrle_encode(src, n, scratch);
            bool l = try_compress >= 2 && n >= 64 &&
                     lzb_encode(src, n, scratch2);
            if (l && (!z || scratch2.size() < scratch.size())) {
                o.push_back(2);
                put_u64(o, scratch2.size());
                o.insert(o.end(), scratch2.begin(), scratch2.end());
            } else if (z) {
                o.push_back(1);
                put_u64(o, scratch.size());
                o.insert(o.end(), scratch.begin(), scratch.end());
            } else {
                o.push_back(0);
                put_u64(o, n);
                o.insert(o.end(), src, src + n);
            }
        }
    }
    *out_len = o.size();
    return f;
}

const uint8_t *frame_data(void *frame) {
    return static_cast<FrameBuf *>(frame)->bytes.data();
}

void frame_release(void *frame) { delete static_cast<FrameBuf *>(frame); }

// parse header only: fills nrows/ncols and per-buffer lengths so the caller
// can allocate destinations, then frame_deserialize copies/decodes into them
int frame_header(const uint8_t *src, uint64_t src_len, uint64_t *nrows,
                 uint32_t *ncols, uint64_t *lens /*cap 3*max_cols*/,
                 uint8_t *dtype_codes, uint32_t max_cols) {
    if (src_len < 16) return -1;
    uint32_t magic;
    std::memcpy(&magic, src, 4);
    if (magic != 0x31464354u) return -2;
    uint32_t nc;
    std::memcpy(&nc, src + 4, 4);
    if (nc > max_cols) return -3;
    if (src_len < 16 + 26ull * nc) return -4;  // truncated header
    std::memcpy(nrows, src + 8, 8);
    *ncols = nc;
    const uint8_t *p = src + 16;
    for (uint32_t c = 0; c < nc; c++) {
        dtype_codes[c] = p[0];
        std::memcpy(&lens[c * 3 + 0], p + 2, 8);
        std::memcpy(&lens[c * 3 + 1], p + 10, 8);
        std::memcpy(&lens[c * 3 + 2], p + 18, 8);
        p += 26;
    }
    return static_cast<int>(p - src);  // offset where buffer section starts
}

int frame_deserialize(const uint8_t *src, uint64_t src_len,
                      uint8_t **dst_bufs, const uint64_t *lens,
                      uint32_t ncols, int header_off) {
    const uint8_t *p = src + header_off;
    const uint8_t *end = src + src_len;
    for (uint32_t c = 0; c < ncols; c++) {
        for (int k = 0; k < 3; k++) {
            uint64_t n = lens[c * 3 + k];
            if (!dst_bufs[c * 3 + k] || n == 0) continue;
            if (end - p < 9) return -1;
            uint8_t codec = *p++;
            uint64_t enc_len;
            std::memcpy(&enc_len, p, 8);
            p += 8;
            if (enc_len > static_cast<uint64_t>(end - p)) return -2;
            if (codec == 0) {
                // raw buffers are written at exactly the header length;
                // a shorter payload is truncation (uninitialized tail)
                if (enc_len != n) return -3;
                std::memcpy(dst_bufs[c * 3 + k], p, enc_len);
            } else if (codec == 1) {
                if (zrle_decode(p, enc_len, dst_bufs[c * 3 + k], n) != 0)
                    return -4;
            } else if (codec == 2) {
                if (lzb_decode(p, enc_len, dst_bufs[c * 3 + k], n) != 0)
                    return -5;
            } else {
                return -6;  // unknown codec byte
            }
            p += enc_len;
        }
    }
    return 0;
}

// ---------------------------------------------------------------------------
// 3. Spill pager: streamed single-file write/read for spilled frames
// (RapidsDiskStore analog; avoids the npz/zip overhead of the Python path).
// ---------------------------------------------------------------------------

int64_t pager_write(const char *path, const uint8_t *data, uint64_t len) {
#if defined(__unix__) || defined(__APPLE__)
    int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC, 0600);
    if (fd < 0) return -1;
    uint64_t off = 0;
    while (off < len) {
        ssize_t w = ::write(fd, data + off, len - off);
        if (w <= 0) {
            ::close(fd);
            return -2;
        }
        off += static_cast<uint64_t>(w);
    }
    ::close(fd);
    return static_cast<int64_t>(off);
#else
    FILE *fp = std::fopen(path, "wb");
    if (!fp) return -1;
    size_t w = std::fwrite(data, 1, len, fp);
    std::fclose(fp);
    return w == len ? static_cast<int64_t>(len) : -2;
#endif
}

int64_t pager_read(const char *path, uint8_t *dst, uint64_t cap) {
#if defined(__unix__) || defined(__APPLE__)
    int fd = ::open(path, O_RDONLY);
    if (fd < 0) return -1;
#ifdef POSIX_FADV_SEQUENTIAL
    ::posix_fadvise(fd, 0, 0, POSIX_FADV_SEQUENTIAL);
#endif
    uint64_t off = 0;
    while (off < cap) {
        ssize_t r = ::read(fd, dst + off, cap - off);
        if (r < 0) {
            ::close(fd);
            return -2;
        }
        if (r == 0) break;
        off += static_cast<uint64_t>(r);
    }
    ::close(fd);
    return static_cast<int64_t>(off);
#else
    FILE *fp = std::fopen(path, "rb");
    if (!fp) return -1;
    size_t r = std::fread(dst, 1, cap, fp);
    std::fclose(fp);
    return static_cast<int64_t>(r);
#endif
}

int64_t pager_file_size(const char *path) {
#if defined(__unix__) || defined(__APPLE__)
    struct stat st;
    if (::stat(path, &st) != 0) return -1;
    return static_cast<int64_t>(st.st_size);
#else
    FILE *fp = std::fopen(path, "rb");
    if (!fp) return -1;
    std::fseek(fp, 0, SEEK_END);
    long n = std::ftell(fp);
    std::fclose(fp);
    return n;
#endif
}

// ---------------------------------------------------------------------------
// 4. Multithreaded file prefetcher (the multithreaded-reader strategy's
// CPU thread pool: background threads read whole files into memory while
// the device decodes previous ones).
// ---------------------------------------------------------------------------

struct PrefetchTask {
    std::string path;
    std::vector<uint8_t> data;
    int64_t status = 0;  // >=0 bytes read, <0 error
    bool done = false;
};

struct Prefetcher {
    std::mutex mu;
    std::condition_variable cv_work, cv_done;
    std::deque<size_t> queue;
    // deque, not vector: prefetcher_submit appends while workers hold
    // references to in-flight tasks; vector reallocation would invalidate
    // them (use-after-free under io/multifile.py's sliding-window submits).
    // deque guarantees element addresses are stable under push_back.
    std::deque<PrefetchTask> tasks;
    std::vector<std::thread> threads;
    bool stop = false;

    explicit Prefetcher(int nthreads) {
        for (int i = 0; i < nthreads; i++)
            threads.emplace_back([this] { worker(); });
    }

    void worker() {
        for (;;) {
            PrefetchTask *tp;
            {
                std::unique_lock<std::mutex> lock(mu);
                cv_work.wait(lock, [this] { return stop || !queue.empty(); });
                if (stop && queue.empty()) return;
                size_t idx = queue.front();
                queue.pop_front();
                tp = &tasks[idx];  // element address stable outside the lock
            }
            PrefetchTask &t = *tp;
            int64_t sz = pager_file_size(t.path.c_str());
            if (sz < 0) {
                t.status = -1;
            } else {
                t.data.resize(static_cast<size_t>(sz));
                t.status = pager_read(t.path.c_str(), t.data.data(),
                                      static_cast<uint64_t>(sz));
            }
            {
                std::lock_guard<std::mutex> lock(mu);
                t.done = true;
            }
            cv_done.notify_all();
        }
    }

    ~Prefetcher() {
        {
            std::lock_guard<std::mutex> lock(mu);
            stop = true;
        }
        cv_work.notify_all();
        for (auto &th : threads) th.join();
    }
};

void *prefetcher_create(int nthreads) {
    return new Prefetcher(nthreads > 0 ? nthreads : 4);
}

// submit all paths up front; returns count
int prefetcher_submit(void *pf, const char **paths, int npaths) {
    Prefetcher *p = static_cast<Prefetcher *>(pf);
    {
        std::lock_guard<std::mutex> lock(p->mu);
        size_t base = p->tasks.size();
        for (int i = 0; i < npaths; i++) {
            p->tasks.emplace_back();
            p->tasks.back().path = paths[i];
            p->queue.push_back(base + i);
        }
    }
    p->cv_work.notify_all();
    return npaths;
}

// block until task idx is done; returns byte count (<0 error)
int64_t prefetcher_wait(void *pf, int idx) {
    Prefetcher *p = static_cast<Prefetcher *>(pf);
    std::unique_lock<std::mutex> lock(p->mu);
    p->cv_done.wait(lock, [&] {
        return static_cast<size_t>(idx) < p->tasks.size() &&
               p->tasks[idx].done;
    });
    PrefetchTask &t = p->tasks[idx];
    return t.status;
}

const uint8_t *prefetcher_data(void *pf, int idx) {
    Prefetcher *p = static_cast<Prefetcher *>(pf);
    std::lock_guard<std::mutex> lock(p->mu);
    return p->tasks[idx].data.data();
}

// drop a completed task's buffer
void prefetcher_release(void *pf, int idx) {
    Prefetcher *p = static_cast<Prefetcher *>(pf);
    std::lock_guard<std::mutex> lock(p->mu);
    std::vector<uint8_t>().swap(p->tasks[idx].data);
}

void prefetcher_destroy(void *pf) { delete static_cast<Prefetcher *>(pf); }

}  // extern "C"
