"""TPU live-window capture: run the moment the axon tunnel is up.

The chip appears in ~5-minute windows (NOTES_r4.md); four rounds have
produced zero captured TPU numbers.  This script is the pre-warmed
"ambush" payload (VERDICT round 4, Next #1): given a live device it
executes, in priority order, saving artifacts incrementally so a window
that dies mid-way still leaves evidence:

  (a) TPC-H q6 + q1-shaped coded group-by  -> BENCH_tpu_capture.json
  (b) both Pallas kernels executed for real -> same file, "pallas" key
  (c) CBO calibration with TPU provenance   -> plan/cbo_weights.json
  (d) a jax profiler trace for MFU analysis -> tpu_trace/ dir

Each phase is wrapped so a tunnel death mid-phase keeps earlier
results.  Run under a timeout from tpu_ambush.sh; never probes — the
caller already did.
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "BENCH_tpu_capture.json")
_T0 = time.monotonic()

state = {"captured_at_s": 0.0, "phases": []}


def log(msg):
    print(f"capture[{time.monotonic() - _T0:6.1f}s]: {msg}",
          file=sys.stderr, flush=True)


def save():
    state["captured_at_s"] = round(time.monotonic() - _T0, 1)
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f, indent=1)
    os.replace(tmp, OUT)


def phase(name):
    def deco(fn):
        def run(*a, **k):
            t0 = time.monotonic()
            try:
                fn(*a, **k)
                state["phases"].append(
                    {"name": name, "ok": True,
                     "s": round(time.monotonic() - t0, 1)})
                log(f"phase {name} ok ({time.monotonic() - t0:.1f}s)")
            except Exception as e:  # noqa: BLE001 - salvage everything
                state["phases"].append(
                    {"name": name, "ok": False, "error": repr(e)[:300],
                     "s": round(time.monotonic() - t0, 1)})
                log(f"phase {name} FAILED: {e!r}")
            save()
        return run
    return deco


def main():
    sys.path.insert(0, REPO)
    import jax
    dev = jax.devices()[0]
    state["device"] = dev.platform
    state["device_kind"] = getattr(dev, "device_kind", "?")
    state["n_devices"] = len(jax.devices())
    save()
    log(f"device: {dev.platform}:{state['device_kind']}")
    if dev.platform != "tpu":
        log("not a TPU; aborting (ambush mis-probe)")
        state["error"] = "not_tpu"
        save()
        return

    import numpy as np

    import bench as B
    from spark_rapids_tpu.api.session import TpuSession

    session = TpuSession()

    # ---- (a) headline bench: q6 + coded group-by ----------------------
    @phase("bench_q6_q1")
    def bench_phase():
        small = B.gen_host(1 << 16)
        eng, _ = B.time_query(
            B.make_q6(session, session.create_dataframe(small)),
            budget=5.0, max_iters=1)
        ref, _ = B.pandas_q6(small, max_iters=1)
        rel = abs(eng - ref) / max(abs(ref), 1e-9)
        state["correctness"] = "ok" if rel < 1e-6 else f"rel={rel:.2e}"
        save()

        pd_n = 1 << 21
        data = B.gen_host(pd_n)
        _, t6 = B.pandas_q6(data, max_iters=2)
        _, t1 = B.pandas_q1(data, max_iters=2)
        q6_base, q1_base = pd_n / t6, pd_n / t1
        state["pandas_q6_rows_per_sec"] = round(q6_base)
        state["pandas_q1_rows_per_sec"] = round(q1_base)
        del data
        save()

        for shift in (22, 24, 26):
            n = 1 << shift
            batch = B.gen_device_batch(n)
            df = session.create_dataframe(batch)
            r6, t6 = B.time_query(B.make_q6(session, df), budget=10.0)
            assert np.isfinite(r6) and r6 > 0
            state.update(metric="tpch_q6_rows_per_sec",
                         value=round(n / t6), unit="rows/s",
                         vs_baseline=round(n / t6 / q6_base, 3))
            save()
            log(f"q6 n=2^{shift}: {n / t6 / 1e6:.1f}M rows/s "
                f"({state['vs_baseline']}x pandas)")
            r1, t1 = B.time_query(B.make_q1(session, df), budget=10.0)
            assert len(r1) == 6
            state["groupby_rows_per_sec"] = round(n / t1)
            state["groupby_vs_baseline"] = round(n / t1 / q1_base, 3)
            save()
            log(f"q1 n=2^{shift}: {n / t1 / 1e6:.1f}M rows/s "
                f"({state['groupby_vs_baseline']}x pandas)")

    bench_phase()

    # ---- (b) Pallas kernels on silicon --------------------------------
    @phase("pallas")
    def pallas_phase():
        import jax.numpy as jnp

        from spark_rapids_tpu.ops import pallas_kernels as pk
        n = 1 << 20
        rng = np.random.default_rng(0)
        pids = jnp.asarray(rng.integers(0, 64, n).astype(np.int32))
        mask = jnp.asarray(rng.random(n) < 0.9)
        got = np.asarray(pk.partition_histogram(pids, mask, 64))
        want = np.asarray(pk.partition_histogram_xla(pids, mask, 64))
        assert (got == want).all(), "partition_histogram mismatch"
        t0 = time.perf_counter()
        pk.partition_histogram(pids, mask, 64)[0].block_until_ready()
        hist_ms = (time.perf_counter() - t0) * 1e3

        vals = [jnp.asarray(rng.uniform(-10, 10, n)) for _ in range(4)]
        vmask = [jnp.asarray(rng.random(n) < 0.95) for _ in range(4)]
        g2 = pk.masked_multi_reduce(vals, vmask, mask)
        w2 = pk.masked_multi_reduce_xla(vals, vmask, mask)
        for a, b in zip(np.asarray(g2).ravel(), np.asarray(w2).ravel()):
            assert abs(a - b) / max(abs(b), 1e-9) < 1e-6
        t0 = time.perf_counter()
        jax.block_until_ready(pk.masked_multi_reduce(vals, vmask, mask))
        reduce_ms = (time.perf_counter() - t0) * 1e3
        state["pallas"] = {"partition_histogram_ms": round(hist_ms, 3),
                           "masked_multi_reduce_ms": round(reduce_ms, 3),
                           "used_pallas": bool(pk.use_pallas()),
                           "verified": True}

    pallas_phase()

    # ---- (c) CBO calibration with TPU provenance ----------------------
    @phase("cbo_calibrate")
    def cbo_phase():
        from spark_rapids_tpu.tools import cbo_calibrate as cc
        result = cc.calibrate(n=1 << 19)
        out = cc.DEFAULT_OUT
        with open(out, "w", encoding="utf-8") as f:
            json.dump(result, f, indent=1, sort_keys=True)
            f.write("\n")
        state["cbo_weights"] = {
            "platform": result["provenance"]["platform"],
            "n_ops": len(result["weights"])}

    cbo_phase()

    # ---- (d) profiler trace for MFU -----------------------------------
    @phase("profiler_trace")
    def trace_phase():
        trace_dir = os.path.join(REPO, "tpu_trace")
        batch = B.gen_device_batch(1 << 24)
        df = session.create_dataframe(batch)
        q = B.make_q6(session, df)
        q()  # warm
        with jax.profiler.trace(trace_dir):
            q()
        state["trace_dir"] = trace_dir

    trace_phase()
    state["done"] = True
    save()
    log("capture complete")


if __name__ == "__main__":
    main()
