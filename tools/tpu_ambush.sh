#!/bin/bash
# Continuous TPU ambush loop (VERDICT r4 Next #1): probe every cycle with
# a short timeout; the moment jax.devices() answers with a TPU, fire
# tpu_capture.py.  Exits 0 on a successful capture (BENCH_tpu_capture.json
# with device=tpu and a nonzero value), 1 when MAX_SECONDS elapse.
#
# Each dark probe costs ~PROBE_TIMEOUT of a hung subprocess — cheap.
# Logs to tpu_ambush.log.

set -u
cd "$(dirname "$0")/.."
MAX_SECONDS=${MAX_SECONDS:-39600}   # 11h
PROBE_TIMEOUT=${PROBE_TIMEOUT:-50}
CAPTURE_TIMEOUT=${CAPTURE_TIMEOUT:-900}
LOG=tpu_ambush.log
T0=$(date +%s)

log() { echo "ambush[$(( $(date +%s) - T0 ))s]: $*" >> "$LOG"; }

log "start (probe ${PROBE_TIMEOUT}s, capture ${CAPTURE_TIMEOUT}s, max ${MAX_SECONDS}s)"
n=0
while true; do
  now=$(date +%s)
  if (( now - T0 > MAX_SECONDS )); then
    log "budget exhausted after $n probes; giving up"
    exit 1
  fi
  n=$((n+1))
  plat=$(timeout "$PROBE_TIMEOUT" python -c \
    'import jax; print(jax.devices()[0].platform)' 2>/dev/null | tail -1)
  if [ "$plat" = "tpu" ]; then
    log "probe #$n LIVE — firing capture"
    timeout "$CAPTURE_TIMEOUT" python tools/tpu_capture.py >> "$LOG" 2>&1
    rc=$?
    log "capture rc=$rc"
    if python - <<'EOF' 2>/dev/null
import json, sys
d = json.load(open("BENCH_tpu_capture.json"))
sys.exit(0 if d.get("device") == "tpu" and d.get("value", 0) > 0 else 1)
EOF
    then
      log "capture SUCCESS"
      exit 0
    fi
    log "capture incomplete; continuing to probe"
  fi
  sleep 15
done
